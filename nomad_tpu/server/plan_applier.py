"""The plan applier: THE serialization point of the cluster.

Reference semantics: nomad/plan_apply.go — planApply:71 single goroutine,
evaluatePlan:400 (per-node feasibility against the freshest snapshot),
partial commits set RefreshIndex to force worker state refresh,
preemption follow-up evals:287-310. Like the reference (optimistic
pipelining, big comment plan_apply.go:44-70), plan N's quorum
replication overlaps plan N+1's verification: the majority-ack wait is
handed to a committer thread that resolves plan futures in commit
order, and — because the FSM applies only at commit on a clustered
leader — plan N's results are overlaid onto the snapshot when
verifying N+1 (the reference applies the result to its private
snapshot for exactly this reason). Verification batches all touched
nodes at once (the EvaluatePool:NumCPU/2 goroutines become one
vectorized pass).

GROUP COMMIT (the r9 departure from the reference): where
plan_apply.go pops ONE plan per iteration, this applier drains every
queued plan — bounded by `ServerConfig.plan_group_max` — and commits
the whole group as ONE raft entry ("plan_group_results"), ONE state
store transaction (a single LayerMap layer push instead of N), and ONE
event-broker flush, with per-plan results demultiplexed back onto each
submitter's future. Verification stays order-equivalent to sequential
apply: all plans verify against one snapshot, and each later plan sees
the earlier group members' node claims through the same overlay
mechanism the pipelined commit already uses — an intra-group loser
demotes to a partial result exactly as a stale-snapshot retry would,
with its refresh fence pointed at the group's commit index so the
retry sees why it lost. `plan_group_max=1` or `NOMAD_TPU_PLAN_GROUP=0`
reproduce the one-entry-per-plan path bit for bit (the bisection
escape hatch); the governor shrinks the group bound under conflict
churn (`governor_plan_group_conflict_high`) and re-widens it after a
clean streak.
"""

from __future__ import annotations

import os
import threading
import time as _time
from collections import deque
from typing import Dict, List, Optional, Tuple

from .. import trace
from ..chaos import faults as chaos_faults
from ..models import (
    Allocation, AllocsFit, Evaluation, Plan, PlanResult,
    EVAL_STATUS_PENDING,
)
from ..models.evaluation import TRIGGER_PREEMPTION
from .plan_queue import PendingPlan, PlanQueue
from ..utils.locks import make_lock

PLAN_GROUP_ENV = "NOMAD_TPU_PLAN_GROUP"

# conflict-churn accounting: intra-group demotions within this window
# feed the `plan_group.conflict_retries` governor gauge, whose
# watermark shrinks the group bound instead of letting retries thrash
CONFLICT_WINDOW_S = 10.0
# consecutive conflict-free groups before a shrunk bound re-widens
GROUP_RECOVER_CLEAN = 32

# process-wide accounting (the BUILD_STATS idiom): bench.py reads this
# after a run so group sizing is attributable across every server the
# bench spun up. Written only by applier threads; racy reads are fine.
GROUP_STATS: Dict[str, int] = {
    "groups": 0, "plans": 0, "conflict_retries": 0,
    "singleton_fallbacks": 0, "max_size": 0,
}


def fail_futures(pairs, exc: Exception) -> None:
    """Fail every unresolved future in a demux pair list — the shared
    abort tail of the group-commit planes (r9 plan groups, r19 ingest
    batches): whatever already resolved keeps its result, everything
    still parked sees the error."""
    for future, _r in pairs:
        if not future.done():
            future.set_exception(exc)


def _count_placements(result) -> int:
    """Fresh placements in a verified plan result — the
    `nomad.plan.placements` counter the telemetry ring rates. Plans
    also carry in-place and attribute updates through node_allocation
    (scheduler/generic.py append_alloc); those allocs are store copies
    with a stamped create_index, while a NEW placement's is still 0
    until the commit stamps it — counting everything would show
    phantom placements/s during a rolling in-place update."""
    return sum(1 for v in result.node_allocation.values()
               for a in v if a.create_index == 0)


def group_commit_enabled() -> bool:
    """The bisection escape hatch: NOMAD_TPU_PLAN_GROUP=0 forces the
    one-raft-entry-per-plan path regardless of plan_group_max."""
    return os.environ.get(PLAN_GROUP_ENV, "1") not in ("0", "off", "no")


class PlanApplier:
    def __init__(self, queue: PlanQueue, server):
        self.queue = queue
        self.server = server      # provides .store and .raft_apply()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._committer: Optional[threading.Thread] = None
        # (pairs, waiter, group index) handed from the verify/apply
        # loop to the committer; pairs is [(future, result)] for one
        # plan OR one whole group. maxsize=1 bounds the pipeline to ONE
        # in-flight commit, matching the reference's overlap of exactly
        # plan N's raft apply with plan N+1's verification
        # (plan_apply.go:56-70); without the bound a partitioned leader
        # would stack local-only applies and serve each submitter its
        # 10s failure in series
        self._commit_q = None
        # submitted-but-not-yet-applied plan results (applier thread
        # only): with apply-at-commit the store lags the log, so N+1's
        # verification must see N's placements or two optimistic plans
        # could double-book one node's capacity
        self._pending: List = []        # [(raft index, PlanResult)]
        # indexes of submitted plans whose commit FAILED — only those
        # leave the overlay early; sibling in-flight plans may still
        # commit and must keep occupying capacity until applied
        self._failed_pending: set = set()
        self._failed_l = make_lock()
        # per-applier group accounting (the governor gauges read these;
        # GROUP_STATS above is the cross-server bench aggregate)
        self.stats: Dict[str, int] = {
            "groups": 0, "plans": 0, "conflict_retries": 0,
            "singleton_fallbacks": 0,
        }
        # adaptive group bound: None == config max; the governor's
        # conflict watermark halves it, clean streaks re-widen it
        self._group_bound: Optional[int] = None
        self._clean_groups = 0
        self._conflicts: deque = deque()
        self._conflict_l = make_lock()

    def start(self) -> None:
        import queue as queue_mod
        self._commit_q = queue_mod.Queue(maxsize=1)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="plan-applier")
        self._thread.start()
        self._committer = threading.Thread(target=self._commit_loop,
                                           daemon=True,
                                           name="plan-committer")
        self._committer.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        # the applier thread is dead (or wedged past the join timeout):
        # send the committer its shutdown sentinel, which it processes
        # after any in-flight commit, then fail whatever remains
        if self._commit_q is not None:
            for _ in range(25):
                try:
                    self._commit_q.put(None, timeout=0.2)
                    break
                except Exception:
                    continue
        if self._committer:
            self._committer.join(timeout=5)
        if self._commit_q is not None:
            while True:
                try:
                    item = self._commit_q.get_nowait()
                except Exception:
                    break
                if item is None:
                    continue
                pairs, _w, _gi = item
                fail_futures(pairs, RuntimeError("plan applier stopped"))

    # -- group sizing / governor hooks ---------------------------------
    def effective_group_bound(self) -> int:
        """Current drain bound: the config max, shrunk by the
        governor's conflict reclaim, 1 when the env kill switch is
        thrown (bisection)."""
        if not group_commit_enabled():
            return 1
        cfg = max(1, int(getattr(self.server.config,
                                 "plan_group_max", 1) or 1))
        b = self._group_bound
        return cfg if b is None else max(1, min(b, cfg))

    def mean_group_size(self) -> float:
        g = self.stats["groups"]
        return self.stats["plans"] / g if g else 0.0

    def conflict_pressure(self) -> int:
        """Intra-group demotions within the sliding window — the
        governor gauge the conflict watermark reads (a monotone total
        would cross once and latch over forever)."""
        now = _time.monotonic()
        with self._conflict_l:
            while self._conflicts and \
                    now - self._conflicts[0] > CONFLICT_WINDOW_S:
                self._conflicts.popleft()
            return len(self._conflicts)

    def shrink_group_bound(self) -> dict:
        """Governor reclaim for `governor_plan_group_conflict_high`:
        halve the group bound so optimistic siblings stop trampling
        each other, instead of letting every demoted plan burn a
        verify-retry round trip. Recovery is automatic (_note_group)."""
        cfg = max(1, int(getattr(self.server.config,
                                 "plan_group_max", 1) or 1))
        cur = self._group_bound if self._group_bound is not None else cfg
        self._group_bound = max(1, cur // 2)
        self._clean_groups = 0
        return {"plan_group_bound": self._group_bound, "was": cur}

    def _note_group(self, size: int, conflicts: int,
                    singleton: bool = False) -> None:
        self.stats["groups"] += 1
        self.stats["plans"] += size
        GROUP_STATS["groups"] += 1
        GROUP_STATS["plans"] += size
        if size > GROUP_STATS["max_size"]:
            GROUP_STATS["max_size"] = size
        if singleton:
            self.stats["singleton_fallbacks"] += 1
            GROUP_STATS["singleton_fallbacks"] += 1
        if conflicts:
            self.stats["conflict_retries"] += conflicts
            GROUP_STATS["conflict_retries"] += conflicts
            now = _time.monotonic()
            with self._conflict_l:
                self._conflicts.extend([now] * conflicts)
            self._clean_groups = 0
        else:
            self._clean_groups += 1
            if self._group_bound is not None and \
                    self._clean_groups >= GROUP_RECOVER_CLEAN:
                self._clean_groups = 0
                cfg = max(1, int(getattr(self.server.config,
                                         "plan_group_max", 1) or 1))
                widened = min(cfg, self._group_bound * 2)
                self._group_bound = None if widened >= cfg else widened

    # -- the applier loop ----------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            bound = self.effective_group_bound()
            if bound > 1:
                group = self.queue.dequeue_group(bound, timeout_s=0.2)
            else:
                pending = self.queue.dequeue(timeout_s=0.2)
                group = [pending] if pending is not None else []
            if not group:
                continue
            if len(group) == 1:
                # the escape hatch AND the idle-queue common case: one
                # plan commits through the unchanged singleton path
                # ("plan_results" raft entries), so plan_group_max=1 /
                # NOMAD_TPU_PLAN_GROUP=0 reproduce the r8 pipeline
                pending = group[0]
                try:
                    result, waiter = self.apply(pending.plan)
                except Exception as e:
                    pending.future.set_exception(e)
                    continue
                self._note_group(1, 0, singleton=True)
                item = ([(pending.future, result)], waiter,
                        result.alloc_index)
            else:
                try:
                    pairs, waiter, index = self.apply_group(group)
                except Exception as e:  # pragma: no cover - defensive
                    for pending in group:
                        if not pending.future.done():
                            pending.future.set_exception(e)
                    continue
                if not pairs:
                    continue
                item = (pairs, waiter, index)
            # hand the quorum wait to the committer and move on to
            # verifying the next group (pipelined commit); blocks while
            # one commit is already in flight (bounded pipeline)
            placed = False
            while not self._stop.is_set():
                try:
                    self._commit_q.put(item, timeout=0.2)
                    placed = True
                    break
                except Exception:
                    continue
            if not placed:
                fail_futures(item[0], RuntimeError("plan applier stopped"))

    def _commit_loop(self) -> None:
        from ..utils import stages
        while True:
            try:
                item = self._commit_q.get(timeout=0.2)
            except Exception:
                if self._stop.is_set():
                    return
                continue
            if item is None:            # shutdown sentinel
                return
            pairs, waiter, group_index = item
            try:
                if waiter is not None:
                    c0 = _time.perf_counter() if stages.enabled else 0.0
                    waiter()
                    if stages.enabled:
                        wdt = _time.perf_counter() - c0
                        stages.add("plan_commit", wdt)
                        # the quorum wait (pipelined behind the next
                        # group's verification) on each member's trace
                        for _future, result in pairs:
                            trace.emit(
                                getattr(result, "_trace", None),
                                "plan_commit", wdt, track="committer",
                                group=len(pairs), index=group_index,
                                phase="quorum")
                # demultiplex: every submitter gets ITS result off the
                # one group commit, in submission order
                for future, result in pairs:
                    if not future.done():
                        future.set_result(result)
            except Exception as e:
                # quorum unreachable / leadership lost: the submitting
                # workers see the failure and nack their evals; THIS
                # group's overlay must not keep rejecting capacity
                # forever (siblings already in flight stay)
                with self._failed_l:
                    if group_index:
                        self._failed_pending.add(group_index)
                fail_futures(pairs, e)

    # -- the core ------------------------------------------------------
    def apply(self, plan: Plan):
        """Verify + locally apply ONE plan. Returns (result, waiter);
        waiter is None or a callable blocking until quorum commit. The
        synchronous test/tool entry `apply_sync` folds the wait in."""
        from ..utils import metrics
        _t0 = _time.monotonic()
        try:
            return self._apply(plan)
        finally:
            metrics.measure_since("nomad.plan.evaluate", _t0)
            metrics.incr_counter("nomad.plan.apply")

    def apply_sync(self, plan: Plan) -> PlanResult:
        result, waiter = self.apply(plan)
        if waiter is not None:
            waiter()
        return result

    def _apply(self, plan: Plan):
        from ..utils import stages
        tr = getattr(plan, "_trace", None)
        self._check_token(plan)
        store = self.server.store
        snapshot = store.snapshot()
        self._retire_pending(snapshot)
        _v0 = _time.perf_counter() if stages.enabled else 0.0
        result, payload, evals, _conflicted = self._verify(snapshot,
                                                           plan, ())
        result._trace = tr      # committer attributes the quorum wait
        if stages.enabled:
            _vdt = _time.perf_counter() - _v0
            stages.add("plan_verify", _vdt)
            trace.emit(tr, "plan_verify", _vdt, track="applier",
                       group=1, demoted=bool(result.refresh_index))
        if payload is None:
            return result, None
        from ..utils import metrics as _metrics
        _metrics.incr_counter("nomad.plan.placements",
                              _count_placements(result))

        # commit through the raft shim (FSM ApplyPlanResults)
        _c0 = _time.perf_counter() if stages.enabled else 0.0
        index, waiter = self.server.raft_apply_async(
            "plan_results", payload)
        if chaos_faults.ACTIVE:
            # same dispatched-not-yet-quorum window as the group path
            # below — the failover cell must trip even when the queue
            # was idle and the plan committed as a singleton
            chaos_faults.fire("plan.group_commit", index=index,
                              plans=1)
        result.alloc_index = index
        if result.refresh_index:
            # partial commit: the accepted slots land at THIS index,
            # above the verify snapshot — the retry's refresh fence
            # must cover them or a remote worker (whose local store
            # lags the leader's) replans from a snapshot that predates
            # the partial commit and re-places slots that already
            # exist (plan_apply.go applyPlan RefreshIndex = max)
            result.refresh_index = max(result.refresh_index, index)
        if waiter is not None:
            # apply-at-commit: the store won't show this plan until the
            # committer's waiter resolves — overlay it for the next
            # verification round
            self._pending.append((index, result))
        for ev in evals:
            self.server.enqueue_eval(ev)
        if stages.enabled:
            _cdt = _time.perf_counter() - _c0
            stages.add("plan_commit", _cdt)
            trace.emit(tr, "plan_commit", _cdt, track="applier",
                       group=1, index=index,
                       pipelined=waiter is not None)
        return result, waiter

    def apply_group(self, group: List[PendingPlan]):
        """Group commit: verify every plan in `group` against ONE
        snapshot — later plans see earlier members' claims through the
        pending-plan overlay, so an intra-group loser demotes to a
        partial result exactly as a stale-snapshot retry would — then
        commit all survivors as ONE raft entry / store transaction /
        event flush. Returns (pairs, waiter, group_index) where pairs
        is [(future, result)] in submission order; futures are resolved
        by the committer, not here. A plan failing the token fence
        fails only its own future and drops out of the group."""
        from ..utils import metrics, stages
        _t0 = _time.monotonic()
        _v0 = _time.perf_counter() if stages.enabled else 0.0
        store = self.server.store
        snapshot = store.snapshot()
        self._retire_pending(snapshot)

        entries: List[Tuple] = []       # (pending, result, payload, evals)
        accepted: List[PlanResult] = []
        conflicts = 0
        for pending in group:
            plan = pending.plan
            tr = getattr(plan, "_trace", None)
            _p0 = _time.perf_counter() if stages.enabled else 0.0
            try:
                self._check_token(plan)
                result, payload, evals, conflicted = self._verify(
                    snapshot, plan, accepted)
            except Exception as e:
                if not pending.future.done():
                    pending.future.set_exception(e)
                continue
            result._trace = tr  # committer attributes the quorum wait
            if stages.enabled:
                # per-plan span with the group anatomy the aggregate
                # window can't carry: width, intra-group conflict,
                # demotion, and how long the plan sat queued behind
                # the serialization point
                trace.emit(
                    tr, "plan_verify", _time.perf_counter() - _p0,
                    track="applier", group=len(group),
                    conflicted=conflicted,
                    demoted=bool(result.refresh_index),
                    queue_ms=round(max(
                        _time.monotonic() - pending.enqueued_t, 0.0)
                        * 1000.0, 3))
            if conflicted:
                conflicts += 1
            entries.append((pending, result, payload, evals))
            if payload is not None:
                accepted.append(result)
                metrics.incr_counter("nomad.plan.placements",
                                     _count_placements(result))
            metrics.incr_counter("nomad.plan.apply")
        metrics.measure_since("nomad.plan.evaluate", _t0)
        if stages.enabled:
            stages.add("plan_verify", _time.perf_counter() - _v0)
        self._note_group(len(group), conflicts)

        pairs = [(pending.future, result)
                 for (pending, result, _p, _e) in entries]
        payloads = [p for (_pe, _r, p, _e) in entries if p is not None]
        if not payloads:
            return pairs, None, 0

        _c0 = _time.perf_counter() if stages.enabled else 0.0
        index, waiter = self.server.raft_apply_async(
            "plan_group_results", dict(groups=payloads))
        if chaos_faults.ACTIVE:
            # chaos hook (ISSUE 16 leader_failover_commit cell): the
            # group's entry is in the leader's log and replicating, but
            # no submitter future has resolved — the exact window where
            # a dying leader must not double-commit (the entry either
            # reaches quorum and survives into the new term, or it
            # never happened; the workers' nack/redelivery covers both)
            chaos_faults.fire("plan.group_commit", index=index,
                              plans=len(payloads))
        for _pending, result, payload, _evs in entries:
            if payload is not None:
                result.alloc_index = index
                if waiter is not None:
                    self._pending.append((index, result))
            if result.refresh_index:
                # a demoted plan's missing capacity becomes visible at
                # the GROUP's commit index, not the snapshot's — point
                # the worker's refresh fence there so the retry sees
                # why it lost instead of replaying the same conflict
                result.refresh_index = max(result.refresh_index, index)
        for _pending, _result, _payload, evals in entries:
            for ev in evals:
                self.server.enqueue_eval(ev)
        if stages.enabled:
            _cdt = _time.perf_counter() - _c0
            stages.add("plan_commit", _cdt)
            # ONE raft entry / store transaction for the whole group:
            # the shared commit span lands on every member's trace
            # with the group size, so a p99 eval's anatomy shows
            # whether it amortized its commit or paid one alone
            for _pending, result, payload, _evs in entries:
                trace.emit(getattr(result, "_trace", None),
                           "plan_commit", _cdt, track="applier",
                           group=len(group), index=index,
                           committed=payload is not None)
        return pairs, waiter, index

    # -- verification --------------------------------------------------
    def _check_token(self, plan: Plan) -> None:
        """Token fence (plan_queue admission in the reference): a plan
        whose eval has been re-delivered (nack timeout mid-process)
        carries a stale token — committing it would double-place the
        job alongside the new holder's plan. Plans from test harness
        paths carry no outstanding eval and pass through."""
        if plan.eval_id and plan.eval_token:
            # tokens come only from worker dequeues, so a tokened plan
            # must still hold the delivery: token mismatch OR a no-
            # longer-outstanding eval (already re-delivered and acked
            # by the new holder) both mean stale
            current = self.server.eval_broker.outstanding(plan.eval_id)
            if current != plan.eval_token:
                raise RuntimeError(
                    f"plan for eval {plan.eval_id} submitted with stale "
                    "token; evaluation was re-delivered")

    def _retire_pending(self, snapshot) -> None:
        """Retire overlay entries the FSM has applied (visible in the
        snapshot now) or whose commit failed. The snapshot is an
        immutable MVCC root, so an entry kept here can never ALSO be
        visible in it — no double counting."""
        with self._failed_l:
            failed, self._failed_pending = self._failed_pending, set()
        latest = snapshot.latest_index()
        self._pending = [(i, r) for (i, r) in self._pending
                         if i > latest and i not in failed]

    def _verify(self, snapshot, plan: Plan, extra):
        """Verify one plan against `snapshot` + the submitted-but-
        unapplied overlay (self._pending) + `extra` (accepted results
        of earlier plans in the same group). Returns (result, payload,
        follow_up_evals, conflicted): payload is None for a no-op
        result; conflicted means a rejection touched a node an `extra`
        result claimed — an intra-group demotion the submitting worker
        will retry."""
        result = PlanResult()
        rejected = False

        # verify each touched node (evaluatePlan / evaluateNodePlan) —
        # one columnar pass over the resident node table for the common
        # shape, scalar fallback for nodes with removals/ports/devices
        verdicts = self._evaluate_nodes(snapshot, plan, extra)
        conflict_nodes = set()
        for r in extra:
            conflict_nodes.update(r.node_allocation)
            conflict_nodes.update(r.node_update)
            conflict_nodes.update(r.node_preemptions)
        conflicted = False
        n_rejected = 0
        for node_id, placements in plan.node_allocation.items():
            if verdicts[node_id]:
                result.node_allocation[node_id] = placements
            else:
                rejected = True
                n_rejected += len(placements)
                if node_id in conflict_nodes:
                    conflicted = True
        if n_rejected:
            from ..utils import metrics
            metrics.incr_counter("nomad.plan.node_rejected", n_rejected)

        # CSI write-claim capacity against the freshest state: two
        # optimistic plans (or two groups in one plan) must not commit
        # more write claimants than the volume's access mode admits
        # (csi.go WriteFreeClaims:385; claims apply per-placement)
        csi_rejected = self._enforce_csi_write_caps(
            snapshot, plan, result.node_allocation, extra)
        if csi_rejected and extra:
            conflicted = True
        rejected = rejected or csi_rejected
        # stops are always committable; preemptions commit only when the
        # placement they made room for was accepted — otherwise victims
        # would be evicted for an alloc that never enters state
        result.node_update = dict(plan.node_update)
        result.node_preemptions = {
            node_id: victims
            for node_id, victims in plan.node_preemptions.items()
            if node_id in result.node_allocation
            or node_id not in plan.node_allocation}
        result.deployment = plan.deployment
        result.deployment_updates = list(plan.deployment_updates)
        if rejected:
            result.refresh_index = snapshot.latest_index()
        if result.is_no_op():
            return result, None, [], conflicted

        stopped = [a for allocs in result.node_update.values()
                   for a in allocs]
        placed = [a for allocs in result.node_allocation.values()
                  for a in allocs]
        preempted = [a for allocs in result.node_preemptions.values()
                     for a in allocs]
        for a in placed:
            if a.job is None:
                a.job = plan.job

        # preempted allocs spawn follow-up evals for their jobs
        # (plan_apply.go:287-310)
        preempted_jobs = set()
        evals: List[Evaluation] = []
        for a in preempted:
            existing = snapshot.alloc_by_id(a.id)
            if existing is None:
                continue
            key = (existing.namespace, existing.job_id)
            if key in preempted_jobs:
                continue
            preempted_jobs.add(key)
            job = snapshot.job_by_id(*key)
            if job is None:
                continue
            evals.append(Evaluation(
                namespace=job.namespace, priority=job.priority,
                type=job.type, triggered_by=TRIGGER_PREEMPTION,
                job_id=job.id, status=EVAL_STATUS_PENDING))

        payload = dict(allocs_stopped=stopped, allocs_placed=placed,
                       allocs_preempted=preempted,
                       deployment=result.deployment,
                       deployment_updates=result.deployment_updates,
                       evals=evals)
        return result, payload, evals, conflicted

    def _overlay_results(self, extra) -> List[PlanResult]:
        """Submitted-but-unapplied results PLUS earlier same-group
        results — everything whose claims the snapshot cannot show."""
        out = [r for _i, r in self._pending]
        out.extend(extra)
        return out

    def _enforce_csi_write_caps(self, snapshot, plan: Plan,
                                node_allocation: Dict[str, List],
                                extra=()) -> bool:
        """Drop placements whose CSI write claims would exceed the
        volume's access mode, budgeting across the whole plan. Mutates
        node_allocation in place; returns True if anything was dropped
        (partial commit => refresh index)."""
        from ..models.csi import (ACCESS_MULTI_NODE_SINGLE_WRITER,
                                  ACCESS_SINGLE_NODE_WRITER)
        budgets: Dict = {}          # (ns, vol_id) -> free write slots
        # submitted-but-unapplied plans (and earlier plans of this
        # group) already hold their write slots
        for pres in self._overlay_results(extra):
            for allocs in pres.node_allocation.values():
                for pa in allocs:
                    pjob = pa.job or snapshot.job_by_id(pa.namespace,
                                                        pa.job_id)
                    ptg = pjob.lookup_task_group(pa.task_group) \
                        if pjob else None
                    for r in (ptg.volumes or {}).values() if ptg else []:
                        if getattr(r, "type", "host") != "csi" or \
                                getattr(r, "read_only", False):
                            continue
                        vol = snapshot.csi_volume(pa.namespace, r.source)
                        if vol is None or vol.access_mode not in (
                                ACCESS_SINGLE_NODE_WRITER,
                                ACCESS_MULTI_NODE_SINGLE_WRITER):
                            continue
                        if pa.id in vol.write_allocs:
                            continue
                        key = (pa.namespace, r.source)
                        if key not in budgets:
                            budgets[key] = 0 if vol.write_allocs else 1
                        budgets[key] -= 1
        dropped = False
        for node_id in list(node_allocation):
            kept = []
            for a in node_allocation[node_id]:
                job = a.job or plan.job or \
                    snapshot.job_by_id(a.namespace, a.job_id)
                tg = job.lookup_task_group(a.task_group) if job else None
                reqs = [r for r in (tg.volumes or {}).values()
                        if getattr(r, "type", "host") == "csi"
                        and not getattr(r, "read_only", False)] if tg else []
                ok = True
                touched = []
                for req in reqs:
                    vol = snapshot.csi_volume(a.namespace, req.source)
                    if vol is None or vol.access_mode not in (
                            ACCESS_SINGLE_NODE_WRITER,
                            ACCESS_MULTI_NODE_SINGLE_WRITER):
                        continue
                    if a.id in vol.write_allocs:
                        continue    # in-place update keeps its claim
                    key = (a.namespace, req.source)
                    if key not in budgets:
                        budgets[key] = 0 if vol.write_allocs else 1
                    if budgets[key] <= 0:
                        ok = False
                        break
                    touched.append(key)
                if ok:
                    for key in touched:
                        budgets[key] -= 1
                    kept.append(a)
                else:
                    dropped = True
            if kept:
                node_allocation[node_id] = kept
            elif node_id in node_allocation:
                del node_allocation[node_id]
        return dropped

    def _res_flags(self, alloc) -> tuple:
        """(has_networks, has_devices), memoized by the resources
        object's identity (plans share flyweight rows). Instance-level:
        the memo's lifetime is this applier's, not the process's."""
        res = alloc.allocated_resources
        if res is None:
            return (False, False)
        memo = self.__dict__.setdefault("_res_flags_memo", {})
        hit = memo.get(id(res))
        if hit is not None and hit[2] is res:
            return hit[:2]
        has_net = bool(res.shared.networks) or any(
            t.networks for t in res.tasks.values())
        has_dev = any(t.devices for t in res.tasks.values())
        if len(memo) > 65536:
            memo.clear()
        memo[id(res)] = (has_net, has_dev, res)
        return has_net, has_dev

    def _evaluate_nodes(self, snapshot, plan: Plan,
                        extra=()) -> Dict[str, bool]:
        """Batched evaluateNodePlan: the reference fans node checks to
        an EvaluatePool of goroutines (plan_apply.go:400); here the
        resident node table turns the common case — placements with no
        removals, ports, or devices on a ready node — into one
        vectorized usage-delta + capacity compare. A 10k-node plan
        verifies in ~50 ms instead of ~10 s of per-node alloc summing.
        Nodes outside the fast shape use the scalar path unchanged.
        `extra` carries earlier same-group results (group commit)."""
        import numpy as np

        from ..ops.tables import _alloc_usage

        items = list(plan.node_allocation.items())
        out: Dict[str, bool] = {}
        table = None
        if len(items) >= 8:
            try:
                # build=False: when the resident table has advanced past
                # this snapshot, a full private build would cost more
                # than the scalar fallback saves
                table = snapshot.node_table(build=False)
            except Exception:
                table = None
        if table is None:
            for node_id, _p in items:
                out[node_id] = self._evaluate_node(snapshot, plan,
                                                   node_id, extra)
            return out

        # overlay usage per node from submitted-but-unapplied plans
        # AND earlier group members, kept per alloc id LAST-WRITE-WINS:
        # an in-place update in the overlay supersedes both the
        # snapshot's copy (subtracted below) and any earlier overlay
        # copy of the same alloc, and a placement in THIS plan that
        # re-uses an overlay alloc's id supersedes it too (the scalar
        # path's placed_ids exclusion) — otherwise the node double-
        # counts one alloc's resources across its versions
        overlay_usage: Dict[str, Dict[str, tuple]] = {}
        overlay_flags: Dict[str, bool] = {}
        for pres in self._overlay_results(extra):
            for node_id, adds in pres.node_allocation.items():
                rows = overlay_usage.setdefault(node_id, {})
                for a in adds:
                    rows[a.id] = _alloc_usage(a)
                    hn, hd = self._res_flags(a)
                    if hn or hd:
                        overlay_flags[node_id] = True
            if pres.node_update or pres.node_preemptions:
                for node_id in list(pres.node_update) + \
                        list(pres.node_preemptions):
                    overlay_flags[node_id] = True

        alloc_by_id = snapshot.alloc_by_id
        idx_get = table.id_to_idx.get
        cand_idx: List[int] = []
        cand_nodes: List[str] = []
        deltas: List[tuple] = []
        for node_id, placements in items:
            i = idx_get(node_id)
            node = table.nodes[i] if i is not None else None
            if node is None or node.status != "ready" or node.drain \
                    or plan.node_update.get(node_id) \
                    or plan.node_preemptions.get(node_id) \
                    or overlay_flags.get(node_id) \
                    or (node.node_resources is not None
                        and node.node_resources.devices):
                out[node_id] = self._evaluate_node(snapshot, plan,
                                                   node_id, extra)
                continue
            d0 = d1 = d2 = d3 = 0.0
            ok = True
            for a in placements:
                hn, hd = self._res_flags(a)
                if hn or hd:
                    ok = False
                    break
                u = _alloc_usage(a)
                d0 += u[0]
                d1 += u[1]
                d2 += u[2]
                d3 += u[3]
                old = alloc_by_id(a.id)
                if old is not None and not old.terminal_status():
                    # in-place update: the snapshot copy is replaced
                    ou = _alloc_usage(old)
                    d0 -= ou[0]
                    d1 -= ou[1]
                    d2 -= ou[2]
                    d3 -= ou[3]
            if not ok:
                out[node_id] = self._evaluate_node(snapshot, plan,
                                                   node_id, extra)
                continue
            ov = overlay_usage.get(node_id)
            if ov is not None:
                placed_ids = {p.id for p in placements}
                for aid, u in ov.items():
                    if aid in placed_ids:
                        continue
                    d0 += u[0]
                    d1 += u[1]
                    d2 += u[2]
                    d3 += u[3]
                    old = alloc_by_id(aid)
                    if old is not None and not old.terminal_status():
                        # overlay in-place update: the snapshot's live
                        # copy is superseded at commit
                        ou = _alloc_usage(old)
                        d0 -= ou[0]
                        d1 -= ou[1]
                        d2 -= ou[2]
                        d3 -= ou[3]
            cand_idx.append(i)
            cand_nodes.append(node_id)
            deltas.append((d0, d1, d2, d3))
        if cand_idx:
            ii = np.asarray(cand_idx, np.int64)
            dd = np.asarray(deltas, np.float32)
            fits = np.all(
                table.base_used[ii] + dd <= table.capacity[ii] + 1e-6,
                axis=1)
            for node_id, fit in zip(cand_nodes, fits):
                out[node_id] = bool(fit)
        return out

    def _evaluate_node(self, snapshot, plan: Plan, node_id: str,
                       extra=()) -> bool:
        """evaluateNodePlan (plan_apply.go:629): would this node's
        placements fit against the freshest state?"""
        node = snapshot.node_by_id(node_id)
        if node is None:
            return False
        if node.status != "ready" and not plan.node_update.get(node_id):
            return False
        if node.drain or node.status != "ready":
            # placements on draining/non-ready nodes rejected; pure stops ok
            if plan.node_allocation.get(node_id):
                return False

        remove_ids = {a.id for a in plan.node_update.get(node_id, [])}
        remove_ids |= {a.id for a in plan.node_preemptions.get(node_id, [])}
        # In-place updates reuse the alloc ID: the planned version replaces
        # the snapshot version, so drop the old copy before appending or the
        # node double-counts its resources (plan_apply.go:674-678).
        placements = plan.node_allocation.get(node_id, [])
        remove_ids |= {a.id for a in placements}
        # overlay submitted-but-unapplied plans (pipelined commit) and
        # earlier same-group results (group commit): their placements
        # occupy capacity, their stops/preemptions free it. Last write
        # wins per alloc id IN COMMIT ORDER — an overlay in-place
        # update supersedes the snapshot's copy and any earlier overlay
        # copy, exactly what the FSM will do at apply
        overlay_by_id: Dict[str, Optional[Allocation]] = {}
        for pres in self._overlay_results(extra):
            for a in pres.node_update.get(node_id, []):
                overlay_by_id[a.id] = None
            for a in pres.node_preemptions.get(node_id, []):
                overlay_by_id[a.id] = None
            for a in pres.node_allocation.get(node_id, []):
                overlay_by_id[a.id] = a
        remove_ids |= set(overlay_by_id)
        placed_ids = {p.id for p in placements}
        proposed = [a for a in snapshot.allocs_by_node(node_id)
                    if not a.terminal_status() and a.id not in remove_ids]
        proposed.extend(a for a in overlay_by_id.values()
                        if a is not None and a.id not in placed_ids)
        proposed.extend(placements)
        fit, _dim, _used = AllocsFit(
            node, proposed,
            check_devices=bool(node.node_resources.devices))
        return fit
