"""CoreScheduler: the `_core` garbage-collection pseudo-scheduler.

Reference semantics: nomad/core_sched.go — the leader periodically (and
on `nomad system gc`, forced) enqueues `_core` evals whose JobID names
the GC pass (eval-gc / job-gc / node-gc / deployment-gc). A worker
dequeues them like any other eval and runs this scheduler, which deletes
objects older than a threshold. "Older than" is expressed as a raft
index cutoff obtained from the leader's TimeTable (nomad/timetable.go),
so every GC decision is a pure function of indexes in the snapshot.

Forced GC (`JobID == "force-gc"`) uses the max index as cutoff.
"""

from __future__ import annotations

import logging
from typing import List

from ..models import Evaluation, JOB_STATUS_DEAD
from ..models.evaluation import (
    CORE_JOB_DEPLOYMENT_GC, CORE_JOB_EVAL_GC, CORE_JOB_FORCE_GC,
    CORE_JOB_JOB_GC, CORE_JOB_NODE_GC,
)

LOG = logging.getLogger("nomad_tpu.core_sched")


class CoreScheduler:
    """Processes one `_core` eval against a state snapshot. Deletions go
    through the server's raft_apply so they hit the WAL like any FSM op."""

    def __init__(self, snapshot, server):
        self.snap = snapshot
        self.srv = server

    # -- entry ---------------------------------------------------------
    def process(self, ev: Evaluation) -> None:
        job = ev.job_id
        if job == CORE_JOB_EVAL_GC:
            self._eval_gc(self._cutoff(self.srv.config.eval_gc_threshold_s))
            self._service_gc()
        elif job == CORE_JOB_JOB_GC:
            self._job_gc(self._cutoff(self.srv.config.job_gc_threshold_s))
        elif job == CORE_JOB_NODE_GC:
            self._node_gc(self._cutoff(self.srv.config.node_gc_threshold_s))
        elif job == CORE_JOB_DEPLOYMENT_GC:
            self._deployment_gc(
                self._cutoff(self.srv.config.deployment_gc_threshold_s))
        elif job == CORE_JOB_FORCE_GC:
            cutoff = 1 << 62
            self._deployment_gc(cutoff)
            self._eval_gc(cutoff)
            self._job_gc(cutoff)
            self._node_gc(cutoff)
            self._service_gc()
        else:
            LOG.warning("unknown core gc job %r", job)

    def _cutoff(self, threshold_s: float) -> int:
        import time
        return self.srv.time_table.nearest_index(time.time() - threshold_s)

    # -- passes --------------------------------------------------------
    def _eval_gc(self, cutoff: int) -> None:
        """core_sched.go evalGC / gcEval: a terminal eval older than the
        cutoff is collected together with its allocs, but only if every
        alloc is itself GC-able (terminal on both desired+client axes).
        Evals from live batch jobs are retained so reschedule history
        survives (core_sched.go:186-200)."""
        gc_evals: List[str] = []
        gc_allocs: List[str] = []
        for ev in self.snap.evals():
            collect, allocs = self._gc_eval(ev, cutoff)
            if collect:
                gc_evals.append(ev.id)
            gc_allocs.extend(allocs)
        if gc_evals or gc_allocs:
            LOG.info("eval GC: %d evals, %d allocs",
                     len(gc_evals), len(gc_allocs))
            self.srv.raft_apply("eval_delete",
                                dict(eval_ids=gc_evals, alloc_ids=gc_allocs))

    def _gc_eval(self, ev: Evaluation, cutoff: int):
        if not ev.terminal_status() or ev.modify_index > cutoff:
            return False, []
        job = self.snap.job_by_id(ev.namespace, ev.job_id)
        if ev.type == "batch":
            # retain the eval (and its allocs) unless the job is gone
            # or dead — reschedule tracking for batch reads old allocs
            if job is not None and job.status != JOB_STATUS_DEAD:
                return False, []
        allocs = self.snap.allocs_by_eval(ev.id)
        gc_allocs = []
        all_gc = True
        for a in allocs:
            if self._alloc_gc_able(a, cutoff):
                gc_allocs.append(a.id)
            else:
                all_gc = False
        return all_gc, gc_allocs

    @staticmethod
    def _alloc_gc_able(alloc, cutoff: int) -> bool:
        return (alloc.modify_index <= cutoff
                and alloc.terminal_status()
                and alloc.client_terminal_status())

    def _job_gc(self, cutoff: int) -> None:
        """core_sched.go jobGC: dead, old jobs whose every eval (and every
        alloc) is GC-able are purged outright."""
        for job in self.snap.jobs():
            if job.status != JOB_STATUS_DEAD or job.modify_index > cutoff:
                continue
            if job.is_periodic() and not job.stopped():
                continue
            evals = self.snap.evals_by_job(job.namespace, job.id)
            gc_evals, gc_allocs, all_gc = [], [], True
            for ev in evals:
                if ev.job_id != job.id:
                    continue
                ok, allocs = self._gc_eval(ev, cutoff)
                if ok:
                    gc_evals.append(ev.id)
                    gc_allocs.extend(allocs)
                else:
                    all_gc = False
            # allocs not attached to a collected eval block the job too
            for a in self.snap.allocs_by_job(job.namespace, job.id):
                if not self._alloc_gc_able(a, cutoff):
                    all_gc = False
            if not all_gc:
                continue
            LOG.info("job GC: %s/%s (+%d evals)", job.namespace, job.id,
                     len(gc_evals))
            if gc_evals or gc_allocs:
                self.srv.raft_apply(
                    "eval_delete", dict(eval_ids=gc_evals,
                                        alloc_ids=gc_allocs))
            self.srv.raft_apply(
                "job_deregister", dict(namespace=job.namespace, job_id=job.id,
                                       purge=True, evals=[]))

    def _service_gc(self) -> None:
        """Catalog sweep: a crashed node's client never sends its
        deregistrations, so drop registrations whose alloc is gone or
        terminal, or whose node is down (the reference's equivalent is
        Consul's anti-entropy against the dead agent)."""
        doomed = []
        for reg in self.snap.service_registrations():
            alloc = self.snap.alloc_by_id(reg.alloc_id)
            if alloc is None or alloc.terminal_status():
                doomed.append(reg.id)
                continue
            node = self.snap.node_by_id(reg.node_id)
            if node is None or node.terminal_status():
                doomed.append(reg.id)
        if doomed:
            LOG.info("service GC: %d registrations", len(doomed))
            self.srv.raft_apply("service_registration_delete",
                                dict(ids=doomed))

    def _node_gc(self, cutoff: int) -> None:
        """core_sched.go nodeGC: down nodes past the threshold with no
        remaining (non-GC-able) allocs are deregistered."""
        gc = []
        for node in self.snap.nodes():
            if not node.terminal_status() or node.modify_index > cutoff:
                continue
            allocs = self.snap.allocs_by_node(node.id)
            if any(not a.terminal_status() for a in allocs):
                continue
            gc.append(node.id)
        if gc:
            LOG.info("node GC: %d nodes", len(gc))
            self.srv.raft_apply("node_deregister", dict(node_ids=gc))

    def _deployment_gc(self, cutoff: int) -> None:
        """core_sched.go deploymentGC: terminal deployments past the
        threshold are deleted (their allocs are handled by eval GC)."""
        gc = []
        for d in self.snap.deployments():
            if d.active() or d.modify_index > cutoff:
                continue
            gc.append(d.id)
        if gc:
            LOG.info("deployment GC: %d deployments", len(gc))
            self.srv.raft_apply("deployment_delete", dict(deployment_ids=gc))
