"""Vault token lifecycle — the embedded token authority.

Reference: nomad/vault.go:176 (vaultClient: CreateToken with TTL,
RenewToken, RevokeTokens, the revocation daemon) plus the
state-store accessor tracking (nomad/state/state_store.go
UpsertVaultAccessor / VaultAccessorsByAlloc) that lets ANY leader
revoke tokens it never minted.

No external Vault exists in this build, so the token backend is the
replicated store itself: a token is valid iff its accessor row exists,
is unrevoked, and `now < expire_time` (extended by renewals). That
collapses the reference's two-system dance (Vault holds leases, Nomad
tracks accessors in raft) into one replicated table with the same
observable semantics — derivation, periodic renewal, revocation on
alloc termination, orphan reaping, and failover (a new leader reads
the same table).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List


@dataclass
class VaultAccessor:
    """One derived token lease (structs.go VaultAccessor + the lease
    state Vault itself would hold)."""
    accessor: str = ""
    token: str = ""             # the secret id (vault's own storage role)
    alloc_id: str = ""
    task: str = ""
    node_id: str = ""
    policies: List[str] = field(default_factory=list)
    ttl_s: float = 0.0
    create_time: float = 0.0    # wall clock (epoch s)
    expire_time: float = 0.0    # advanced by every renewal
    create_index: int = 0
    modify_index: int = 0

    def expired(self, now: float = None) -> bool:
        return (now if now is not None else time.time()) >= self.expire_time
