"""Connect admission hook: inject sidecar/gateway proxy tasks.

Reference: nomad/job_endpoint_hook_connect.go — groupConnectHook:174
(mutate) + groupConnectValidate:367. Runs inside Job.Register between
canonicalize and validate. The reference injects a docker/Envoy task;
the driver and config are server-configurable here so the mesh works
with any installed driver (tests use mock).
"""

from __future__ import annotations

from typing import List, Optional

from ..models import (
    CONNECT_INGRESS_PREFIX,
    CONNECT_PROXY_PREFIX,
    CONNECT_NATIVE_PREFIX,
    Job,
)
from ..models.job import LogConfig, Task, TaskGroup, TaskLifecycleConfig
from ..models.networks import Port
from ..models.resources import Resources

# connectSidecarResources (job_endpoint_hook_connect.go:16)
SIDECAR_CPU = 250
SIDECAR_MEMORY_MB = 128

DEFAULT_SIDECAR_DRIVER = "docker"
DEFAULT_SIDECAR_CONFIG = {
    "image": "envoyproxy/envoy:v1.16.0",
    "args": ["-c", "${NOMAD_SECRETS_DIR}/envoy_bootstrap.json",
             "--disable-hot-restart"],
}


def proxy_port_label(service_name: str) -> str:
    return f"{CONNECT_PROXY_PREFIX}-{service_name}"


def _sidecar_for(tg: TaskGroup, svc_name: str) -> Optional[Task]:
    """getSidecarTaskForService:125 — match by Kind, not name."""
    want = f"{CONNECT_PROXY_PREFIX}:{svc_name}"
    for t in tg.tasks:
        if t.kind == want:
            return t
    return None


def _has_gateway_task(tg: TaskGroup, svc_name: str) -> bool:
    want = f"{CONNECT_INGRESS_PREFIX}:{svc_name}"
    return any(t.kind == want for t in tg.tasks)


def _named_task_for_native(tg: TaskGroup, svc_name: str,
                           task_name: str) -> Task:
    """getNamedTaskForNativeService:155 — empty name is inferred only
    for single-task groups."""
    if not task_name:
        if len(tg.tasks) == 1:
            return tg.tasks[0]
        raise ValueError(
            f"task for Consul Connect Native service "
            f"{tg.name}->{svc_name} is ambiguous and must be set")
    for t in tg.tasks:
        if t.name == task_name:
            return t
    raise ValueError(
        f"task {task_name} named by Consul Connect Native service "
        f"{tg.name}->{svc_name} does not exist")


def _new_connect_task(svc_name: str, driver: str, config: dict) -> Task:
    """newConnectTask:344."""
    return Task(
        name=f"{CONNECT_PROXY_PREFIX}-{svc_name}",
        kind=f"{CONNECT_PROXY_PREFIX}:{svc_name}",
        driver=driver,
        config=dict(config),
        shutdown_delay_s=5.0,
        log_config=LogConfig(max_files=2, max_file_size_mb=2),
        resources=Resources(cpu=SIDECAR_CPU, memory_mb=SIDECAR_MEMORY_MB),
        lifecycle=TaskLifecycleConfig(hook="prestart", sidecar=True),
    )


def _new_gateway_task(svc_name: str, driver: str, config: dict) -> Task:
    """newConnectGatewayTask:325."""
    return Task(
        name=f"{CONNECT_INGRESS_PREFIX}-{svc_name}",
        kind=f"{CONNECT_INGRESS_PREFIX}:{svc_name}",
        driver=driver,
        config=dict(config),
        shutdown_delay_s=5.0,
        log_config=LogConfig(max_files=2, max_file_size_mb=2),
        resources=Resources(cpu=SIDECAR_CPU, memory_mb=SIDECAR_MEMORY_MB),
    )


def connect_mutate(job: Job, sidecar_driver: str = DEFAULT_SIDECAR_DRIVER,
                   sidecar_config: Optional[dict] = None) -> None:
    """jobConnectHook.Mutate:91 — groups without networks are skipped
    here so Validate can produce the meaningful error."""
    cfg = sidecar_config if sidecar_config is not None \
        else DEFAULT_SIDECAR_CONFIG
    for tg in job.task_groups:
        if not tg.networks:
            continue
        _group_connect_mutate(job, tg, sidecar_driver, cfg)


def _group_connect_mutate(job: Job, tg: TaskGroup, driver: str,
                          cfg: dict) -> None:
    """groupConnectHook:174."""
    for service in tg.services:
        connect = service.connect
        if connect is None:
            continue
        if connect.has_sidecar():
            task = _sidecar_for(tg, service.name)
            if task is None:
                task = _new_connect_task(service.name, driver, cfg)
                # a same-named unrelated task forces a suffixed name
                if any(t.name == task.name for t in tg.tasks):
                    from ..utils.ids import generate_uuid
                    task.name = f"{task.name}-{generate_uuid()[:6]}"
                tg.tasks.append(task)
            if connect.sidecar_task is not None:
                connect.sidecar_task.merge_into(task)
            task.canonicalize(job, tg)
            # dynamic proxy port, mapped same-port into the netns
            # (To=-1 sentinel, groupConnectHook makePort)
            label = proxy_port_label(service.name)
            if not any(p.label == label
                       for p in tg.networks[0].dynamic_ports):
                tg.networks[0].dynamic_ports.append(
                    Port(label=label, to=-1))
        elif connect.is_native():
            task = _named_task_for_native(tg, service.name,
                                          service.task_name)
            task.kind = f"{CONNECT_NATIVE_PREFIX}:{service.name}"
            service.task_name = task.name
        elif connect.is_gateway():
            if not _has_gateway_task(tg, service.name):
                task = _new_gateway_task(service.name, driver, cfg)
                tg.tasks.append(task)
                task.canonicalize(job, tg)


def expose_check_mutate(job: Job) -> None:
    """jobExposeCheckHook.Mutate (job_endpoint_hook_expose_check.go:22):
    group-service checks with expose=true get an expose path on the
    sidecar proxy, generating a dynamic listener port when the check
    has no port label of its own."""
    from ..models.services import ConsulExposeConfig, ConsulExposePath
    import hashlib
    for tg in job.task_groups:
        for s in tg.services:
            for check in s.checks:
                # checkIsExposable: http/grpc with a rooted path only
                if not check.expose or \
                        check.type.lower() not in ("http", "grpc") or \
                        not check.path.startswith("/"):
                    continue
                # only the BUILT-IN proxy serves expose paths; guard
                # BEFORE any mutation or a sidecar-less service would
                # be left with an orphan port + rewritten check label
                if s.connect is None or \
                        s.connect.sidecar_service is None:
                    continue        # validate() rejects this shape
                if len(tg.networks) != 1 or \
                        tg.networks[0].mode != "bridge":
                    raise ValueError(
                        f"group {tg.name!r} must use bridge network "
                        "for exposing service check(s)")
                if not check.port_label:
                    # DETERMINISTIC label: a random suffix would make
                    # every re-register of an unchanged job look like
                    # a destructive network change
                    digest = hashlib.sha256(
                        f"{s.name}\x00{check.name}".encode()
                    ).hexdigest()[:6]
                    label = f"svc_{s.name}_ck_{digest}"
                    if not any(p.label == label
                               for p in tg.networks[0].dynamic_ports):
                        tg.networks[0].dynamic_ports.append(
                            Port(label=label, to=-1))
                    check.port_label = label
                # local service port — what the service binds INSIDE
                # the netns (structs Networks.Port: reserved ports use
                # their value, dynamic ports their `to` mapping), else
                # a literal port number
                port = 0
                for nw in tg.networks:
                    for p in nw.reserved_ports:
                        if p.label == s.port_label:
                            port = p.value
                    for p in nw.dynamic_ports:
                        if p.label == s.port_label:
                            port = p.to
                    if port > 0:
                        break
                if port <= 0:
                    try:
                        port = int(s.port_label)
                    except ValueError:
                        port = 0
                    if port <= 0:
                        raise ValueError(
                            f"unable to determine local service port "
                            f"for service check {tg.name}->{s.name}->"
                            f"{check.name}")
                ss = s.connect.sidecar_service
                if ss.proxy is None:
                    from ..models.services import ConsulProxy
                    ss.proxy = ConsulProxy()
                if ss.proxy.expose is None:
                    ss.proxy.expose = ConsulExposeConfig()
                new = ConsulExposePath(
                    path=check.path, protocol=check.type.lower(),
                    local_path_port=port,
                    listener_port=check.port_label)
                if new not in ss.proxy.expose.paths:
                    ss.proxy.expose.paths.append(new)


def expose_check_validate(job: Job) -> List[str]:
    """jobExposeCheckHook.Validate:50 — expose only on group services
    with the BUILT-IN connect proxy, in a single bridge network."""
    errs: List[str] = []
    for tg in job.task_groups:
        uses = any(c.expose for s in tg.services for c in s.checks)
        if uses:
            if len(tg.networks) != 1:
                errs.append(
                    f"group {tg.name!r} must specify one bridge "
                    "network for exposing service check(s)")
            elif tg.networks[0].mode != "bridge":
                errs.append(
                    f"group {tg.name!r} must use bridge network for "
                    "exposing service check(s)")
        for s in tg.services:
            for c in s.checks:
                if c.expose and (
                        s.connect is None
                        or s.connect.sidecar_service is None
                        or s.connect.sidecar_task is not None):
                    errs.append(
                        f"exposed service check {tg.name}->{s.name}->"
                        f"{c.name} requires use of the builtin "
                        "Connect proxy")
        for t in tg.tasks:
            for s in t.services:
                for c in s.checks:
                    if c.expose:
                        errs.append(
                            f"exposed service check {tg.name}[{t.name}]"
                            f"->{s.name}->{c.name} is not a task-group "
                            "service")
    return errs


def connect_validate(job: Job) -> List[str]:
    """jobConnectHook.Validate:110 -> groupConnectValidate:367."""
    errs: List[str] = []
    for tg in job.task_groups:
        for s in tg.services:
            connect = s.connect
            if connect is None:
                continue
            if connect.has_sidecar():
                if len(tg.networks) != 1:
                    errs.append(
                        f"Consul Connect sidecars require exactly 1 "
                        f"network, found {len(tg.networks)} in group "
                        f"{tg.name!r}")
                elif tg.networks[0].mode != "bridge":
                    errs.append(
                        f"Consul Connect sidecar requires bridge "
                        f"network, found {tg.networks[0].mode!r} in "
                        f"group {tg.name!r}")
            elif connect.is_native():
                try:
                    _named_task_for_native(tg, s.name, s.task_name)
                except ValueError as e:
                    errs.append(str(e))
            elif connect.is_gateway():
                if len(tg.networks) != 1:
                    errs.append(
                        f"Consul Connect gateways require exactly 1 "
                        f"network, found {len(tg.networks)} in group "
                        f"{tg.name!r}")
                elif tg.networks[0].mode not in ("bridge", "host"):
                    errs.append(
                        'Consul Connect Gateway service requires Task '
                        'Group with network mode of type "bridge" or '
                        '"host"')
    return errs
