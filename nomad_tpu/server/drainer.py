"""Node drainer — staged migration of allocations off draining nodes.

Reference semantics: nomad/drainer/ (drainer.go NodeDrainer:130, run:225;
watch_jobs.go drainingJobWatcher batches migrations honoring the task
group's migrate{max_parallel}; drain_heap.go tracks per-node force
deadlines; watch_nodes.go marks the drain complete when the node has no
more draining allocs). The drainer never stops allocations itself: it
flags DesiredTransition.Migrate on a bounded batch and emits node-drain
evaluations; the reconciler then stops the flagged allocs and places
replacements elsewhere (reconcile_util.go filterByTainted honors the
transition). System-job allocations are drained only after all service/
batch allocations have left (or at the force deadline), matching
watch_nodes.go's service-first ordering; `ignore_system_jobs` leaves them
in place.

Structural translation: one thread re-evaluating all draining nodes on
every store index change plus a deadline tick, same shape as
deployment_watcher.py.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..models import Evaluation, EVAL_STATUS_PENDING, JOB_TYPE_SYSTEM
from ..models.alloc import DesiredTransition
from ..models.evaluation import TRIGGER_NODE_DRAIN

LOG = logging.getLogger("nomad_tpu.drainer")


class NodeDrainer:
    """Leader-only service (leader.go establishLeadership enables it)."""

    TICK_S = 0.25

    def __init__(self, server):
        self.server = server
        self._enabled = False
        self._gen = 0
        self._thread: Optional[threading.Thread] = None

    def set_enabled(self, enabled: bool) -> None:
        if enabled and not self._enabled:
            self._enabled = True
            self._gen += 1
            self._thread = threading.Thread(target=self._run,
                                            args=(self._gen,), daemon=True,
                                            name="node-drainer")
            self._thread.start()
        elif not enabled:
            self._enabled = False

    def _run(self, gen: int) -> None:
        while self._enabled and gen == self._gen:
            snap = self.server.store.snapshot()
            try:
                for node in snap.nodes():
                    if node.drain_strategy is not None:
                        self._drain_node(snap, node)
            except Exception:
                LOG.exception("drain scan failed")
            self.server.store.block_min_index(snap.latest_index() + 1,
                                              timeout_s=self.TICK_S)

    def _drain_node(self, snap, node) -> None:
        strat = node.drain_strategy
        now = time.time()
        force = strat.force_deadline > 0 and now >= strat.force_deadline

        # live allocs still on the node, split by job type
        service: List[Tuple[object, object]] = []   # (alloc, job)
        system: List[Tuple[object, object]] = []
        # client-live allocs only; desired-stop-but-still-running allocs
        # stay in the set so they count against the migrate budget
        for a in snap.allocs_by_node(node.id):
            if a.client_terminal_status():
                continue
            job = a.job or snap.job_by_id(a.namespace, a.job_id)
            if job is not None and job.type == JOB_TYPE_SYSTEM:
                if not strat.drain_spec.ignore_system_jobs:
                    system.append((a, job))
                continue
            service.append((a, job))

        if not service:
            if system:
                # all services gone: evals let the system scheduler stop
                # its allocs (the draining node is no longer "ready").
                # Skip jobs whose allocs are already stopping, or every
                # tick re-emits an identical eval while the client kills.
                pending = {(j.namespace, j.id): j for a, j in system
                           if not a.server_terminal_status()}
                if pending:
                    self._emit_evals(pending)
                return
            LOG.info("node %s drain complete", node.id[:8])
            self.server.update_node_drain(node.id, None, mark_eligible=False)
            return

        # batch service/batch migrations per task group, bounded by
        # migrate.max_parallel minus migrations still in flight
        by_tg: Dict[Tuple[str, str, str], List[Tuple[object, object]]] = {}
        for a, job in service:
            by_tg.setdefault((a.namespace, a.job_id, a.task_group),
                             []).append((a, job))
        to_flag = []
        jobs: Dict[Tuple[str, str], object] = {}
        for (ns, job_id, tg_name), items in by_tg.items():
            job = items[0][1]
            tg = job.lookup_task_group(tg_name) if job else None
            max_parallel = (tg.migrate.max_parallel
                            if tg is not None and tg.migrate is not None else 1)
            if force:
                max_parallel = len(items)
            in_flight = sum(
                1 for a, _ in items
                if a.desired_transition.should_migrate() or a.terminal_status())
            budget = max(0, max_parallel - in_flight)
            for a, _ in items:
                if budget <= 0:
                    break
                if a.desired_transition.should_migrate() or a.terminal_status():
                    continue
                to_flag.append(a)
                jobs[(ns, job_id)] = job
                budget -= 1
        if to_flag:
            self.server.drain_allocs(to_flag, jobs)

    def _emit_evals(self, jobs: Dict[Tuple[str, str], object]) -> None:
        evals = [_drain_eval(job) for job in jobs.values()]
        # skip if an identical pending eval is already queued for the job
        pending = {(e.namespace, e.job_id)
                   for e in self.server.store.evals()
                   if e.status == EVAL_STATUS_PENDING
                   and e.triggered_by == TRIGGER_NODE_DRAIN}
        evals = [e for e in evals if (e.namespace, e.job_id) not in pending]
        if evals:
            self.server.raft_apply("eval_update", dict(evals=evals))


def _drain_eval(job) -> Evaluation:
    return Evaluation(
        namespace=job.namespace, priority=job.priority, type=job.type,
        triggered_by=TRIGGER_NODE_DRAIN, job_id=job.id,
        status=EVAL_STATUS_PENDING)


def drain_allocs(server, allocs, jobs: Dict[Tuple[str, str], object]) -> None:
    """Flag DesiredTransition.Migrate and emit one eval per affected job
    (drainer.go drainAllocs -> AllocUpdateDesiredTransition raft apply)."""
    evals = [_drain_eval(job) for job in jobs.values()]
    server.raft_apply(
        "alloc_desired_transition",
        dict(alloc_ids=[a.id for a in allocs],
             transition=DesiredTransition(migrate=True),
             evals=evals))
    LOG.info("draining %d allocs across %d jobs", len(allocs), len(jobs))
