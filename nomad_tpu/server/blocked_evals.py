"""BlockedEvals: evals that failed placement, woken when capacity frees.

Reference semantics: nomad/blocked_evals.go — Block:166 (captured by
computed class vs escaped), Unblock:418 on node updates,
UnblockClassAndQuota:470, UnblockNode:501, per-job dedup:255,
missed-unblock index check:316.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set, Tuple

from ..models import Evaluation
from ..utils.locks import make_lock

UNBLOCK_CH_SIZE = 256


class _BlockedStats:
    def __init__(self):
        self.total_blocked = 0
        self.total_escaped = 0
        self.total_quota_limit = 0


class BlockedEvals:
    def __init__(self, enqueue_fn: Callable[[Evaluation], None]):
        """enqueue_fn pushes an unblocked eval back into the EvalBroker."""
        self._l = make_lock()
        self._enabled = False
        self._enqueue = enqueue_fn
        # eval id -> (eval, token-ignored)
        self._captured: Dict[str, Evaluation] = {}
        self._escaped: Dict[str, Evaluation] = {}
        # job dedup: (ns, job) -> eval id
        self._job_evals: Dict[Tuple[str, str], str] = {}
        # class -> highest index at which that class was unblocked
        self._unblock_indexes: Dict[str, int] = {}
        # duplicate blocked evals to cancel (leader reaps them)
        self.duplicates: List[Evaluation] = []
        self.stats = _BlockedStats()

    def set_enabled(self, enabled: bool) -> None:
        with self._l:
            self._enabled = enabled
            if not enabled:
                self._captured.clear()
                self._escaped.clear()
                self._job_evals.clear()
                self._unblock_indexes.clear()
                self.duplicates.clear()
                self.stats = _BlockedStats()

    # -- blocking ------------------------------------------------------
    def block(self, ev: Evaluation) -> None:
        with self._l:
            if not self._enabled:
                return
            if ev.id in self._captured or ev.id in self._escaped:
                return
            key = (ev.namespace, ev.job_id)
            existing = self._job_evals.get(key)
            if existing is not None and existing != ev.id:
                # one blocked eval per job: newer wins, older is a duplicate
                old = self._captured.pop(existing, None)
                if old is None:
                    old = self._escaped.pop(existing, None)
                    if old is not None:
                        self.stats.total_escaped -= 1
                if old is not None:
                    self.duplicates.append(old)
                    self.stats.total_blocked -= 1
            self._job_evals[key] = ev.id

            # missed-unblock check: if any eligible class was unblocked at
            # an index beyond the eval's snapshot, immediately unblock
            if self._missed_unblock(ev):
                self._enqueue(ev)
                self._job_evals.pop(key, None)
                return

            if ev.escaped_computed_class:
                self._escaped[ev.id] = ev
                self.stats.total_escaped += 1
            else:
                self._captured[ev.id] = ev
            self.stats.total_blocked += 1

    def _missed_unblock(self, ev: Evaluation) -> bool:
        for cls, index in self._unblock_indexes.items():
            if index <= ev.snapshot_index:
                continue
            elig = ev.class_eligibility.get(cls)
            if elig is None and not ev.escaped_computed_class:
                # untracked class counts as a potential miss only for
                # escaped evals; for captured ones unknown class is skipped
                continue
            if elig is not False:
                return True
        return False

    def untrack(self, namespace: str, job_id: str) -> None:
        """Job updated: blocked evals for it are stale (blocked_evals.go Untrack)."""
        with self._l:
            key = (namespace, job_id)
            eval_id = self._job_evals.pop(key, None)
            if eval_id is None:
                return
            old = self._captured.pop(eval_id, None)
            if old is None:
                old = self._escaped.pop(eval_id, None)
                if old is not None:
                    self.stats.total_escaped -= 1
            if old is not None:
                self.stats.total_blocked -= 1

    # -- unblocking ----------------------------------------------------
    def unblock(self, computed_class: str, index: int) -> None:
        """Capacity changed for a node class: requeue eligible evals."""
        with self._l:
            if not self._enabled:
                return
            self._unblock_indexes[computed_class] = index
            unblock: List[Evaluation] = []
            for eid, ev in list(self._captured.items()):
                elig = ev.class_eligibility.get(computed_class)
                if elig is not False:
                    # eligible or unknown class -> wake it
                    unblock.append(ev)
                    del self._captured[eid]
            for eid, ev in list(self._escaped.items()):
                unblock.append(ev)
                del self._escaped[eid]
                self.stats.total_escaped -= 1
            for ev in unblock:
                self._job_evals.pop((ev.namespace, ev.job_id), None)
                self.stats.total_blocked -= 1
                self._enqueue(ev)

    def unblock_all(self, index: int) -> None:
        with self._l:
            if not self._enabled:
                return
            evals = list(self._captured.values()) + list(self._escaped.values())
            self._captured.clear()
            self._escaped.clear()
            self._job_evals.clear()
            self.stats.total_blocked = 0
            self.stats.total_escaped = 0
            for ev in evals:
                self._enqueue(ev)

    def unblock_quota(self, quota: str, index: int) -> None:
        with self._l:
            if not self._enabled:
                return
            woken = []
            for store in (self._captured, self._escaped):
                for eid, ev in list(store.items()):
                    if ev.quota_limit_reached == quota:
                        woken.append(ev)
                        del store[eid]
                        if store is self._escaped:
                            self.stats.total_escaped -= 1
            for ev in woken:
                self._job_evals.pop((ev.namespace, ev.job_id), None)
                self.stats.total_blocked -= 1
                self._enqueue(ev)

    def get_duplicates(self) -> List[Evaluation]:
        with self._l:
            dups = self.duplicates
            self.duplicates = []
            return dups

    def blocked_count(self) -> int:
        with self._l:
            return len(self._captured) + len(self._escaped)
