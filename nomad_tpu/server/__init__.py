from .eval_broker import EvalBroker
from .blocked_evals import BlockedEvals
from .plan_queue import PlanQueue
from .plan_applier import PlanApplier
from .worker import Worker
from .core import Server, ServerConfig
