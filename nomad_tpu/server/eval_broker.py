"""EvalBroker: priority queue of pending evaluations with the
at-most-one-outstanding-eval-per-job invariant.

Reference semantics: nomad/eval_broker.go — Enqueue:181, Dequeue:329,
Ack:531, Nack:595, nack re-enqueue delays:644, delayed-eval heap:751,
per-job blocked heaps, delivery limit -> failed queue.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..models import Evaluation, JOB_TYPE_CORE
from ..utils.ids import generate_uuid
from ..utils.locks import make_condition

FAILED_QUEUE = "_failed"

DEFAULT_NACK_TIMEOUT_S = 60.0
DEFAULT_DELIVERY_LIMIT = 3
DEFAULT_INITIAL_NACK_DELAY_S = 1.0
DEFAULT_SUBSEQUENT_NACK_DELAY_S = 20.0
# admission-control deferral while the governor signals backpressure:
# shed enqueues park on the delayed heap this long before re-testing
# the pressure gauge
DEFAULT_ADMISSION_DELAY_S = 0.25


class AdmissionOverloadError(Exception):
    """Backpressure escalation (ROADMAP open item): raised by the HTTP
    job-register path when the broker's delayed/requeue heap itself has
    crossed its watermark — the shed valve is full, so new work must be
    refused at the edge (429 + Retry-After) instead of parked."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class _PQ:
    """Priority heap: highest priority first, FIFO by create index."""

    def __init__(self):
        self._h: List[Tuple[int, int, int, Evaluation]] = []
        self._seq = 0

    def push(self, ev: Evaluation) -> None:
        self._seq += 1
        heapq.heappush(self._h, (-ev.priority, ev.create_index, self._seq, ev))

    def pop(self) -> Evaluation:
        return heapq.heappop(self._h)[3]

    def peek(self) -> Optional[Evaluation]:
        return self._h[0][3] if self._h else None

    def __len__(self):
        return len(self._h)


class _Unack:
    __slots__ = ("eval", "token", "nack_timer")

    def __init__(self, ev, token, nack_timer):
        self.eval = ev
        self.token = token
        self.nack_timer = nack_timer


class BrokerStats:
    def __init__(self):
        self.total_ready = 0
        self.total_unacked = 0
        self.total_blocked = 0
        self.total_waiting = 0
        self.total_shed = 0     # admission-control deferrals (governor)

    def as_dict(self):
        return {"ready": self.total_ready, "unacked": self.total_unacked,
                "blocked": self.total_blocked,
                "waiting": self.total_waiting,
                "shed": self.total_shed}


class EvalBroker:
    def __init__(self, nack_timeout_s: float = DEFAULT_NACK_TIMEOUT_S,
                 delivery_limit: int = DEFAULT_DELIVERY_LIMIT,
                 initial_nack_delay_s: float = DEFAULT_INITIAL_NACK_DELAY_S,
                 subsequent_nack_delay_s: float = DEFAULT_SUBSEQUENT_NACK_DELAY_S):
        self.nack_timeout_s = nack_timeout_s
        self.delivery_limit = delivery_limit
        self.initial_nack_delay_s = initial_nack_delay_s
        self.subsequent_nack_delay_s = subsequent_nack_delay_s

        self._l = make_condition()
        self._enabled = False
        self._ready: Dict[str, _PQ] = {}               # queue -> heap
        self._unack: Dict[str, _Unack] = {}            # eval id -> unack
        self._evals: Dict[str, int] = {}               # eval id -> dequeues
        self._job_evals: Dict[Tuple[str, str], str] = {}   # (ns,job)->eval id
        self._blocked: Dict[Tuple[str, str], _PQ] = {} # per-job pending heaps
        self._requeue: Dict[str, Evaluation] = {}      # token -> reblocked eval
        self._time_wait: Dict[str, threading.Timer] = {}
        # wait_until heaps, split by type: core evals (rare, must admit
        # on schedule even under backpressure) park separately so the
        # pressured pop cycle can leave the non-core heap untouched
        self._delayed: List[Tuple[float, int, Evaluation]] = []
        self._delayed_core_q: List[Tuple[float, int, Evaluation]] = []
        self._delay_seq = 0
        self._delay_timer: Optional[threading.Timer] = None
        self._delay_timer_at = 0.0      # absolute fire time when armed
        # governor backpressure: when this returns True, fresh enqueues
        # shed onto the admission-controlled delayed path instead of
        # the ready queue (recovering as soon as the gauge clears)
        self.pressure_fn = None
        self.admission_delay_s = DEFAULT_ADMISSION_DELAY_S
        # escalation stage: when the delayed heap ITSELF exceeds this
        # depth, register_admission() refuses new work (the HTTP path
        # turns that into 429 + Retry-After). 0 disables.
        self.delayed_depth_high = 0
        self.stats = BrokerStats()

    # -- admission escalation ------------------------------------------
    def delayed_depth(self) -> int:
        """Depth of the non-core delayed/requeue heap (the shed
        valve's backlog) — the escalation gauge."""
        return len(self._delayed)

    def check_register_admission(self) -> None:
        """Raise AdmissionOverloadError when the shed valve is full.
        Called by edge paths that CREATE new work (job register); the
        broker's own requeues/nacks are never refused — refusing those
        would lose work already admitted. Retry-After scales with how
        far past the watermark the heap is, in admission windows: the
        deeper the backlog, the longer a well-behaved client should
        stay away."""
        high = self.delayed_depth_high
        if high <= 0:
            return
        depth = len(self._delayed)
        if depth < high:
            return
        retry = max(1.0, self.admission_delay_s
                    * (4.0 * min(depth / high, 8.0)))
        raise AdmissionOverloadError(
            f"eval broker overloaded: {depth} deferred evaluations "
            f"(watermark {high}); retry after {retry:.0f}s",
            retry_after_s=retry)

    # -- lifecycle -----------------------------------------------------
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, enabled: bool) -> None:
        with self._l:
            self._enabled = enabled
        if not enabled:
            self.flush()

    def flush(self) -> None:
        with self._l:
            for unack in self._unack.values():
                unack.nack_timer.cancel()
            for timer in self._time_wait.values():
                timer.cancel()
            if self._delay_timer:
                self._delay_timer.cancel()
                self._delay_timer = None
            self._ready.clear()
            self._unack.clear()
            self._evals.clear()
            self._job_evals.clear()
            self._blocked.clear()
            self._requeue.clear()
            self._time_wait.clear()
            self._delayed.clear()
            self._delayed_core_q.clear()
            self.stats = BrokerStats()
            self._l.notify_all()

    # -- enqueue -------------------------------------------------------
    def enqueue(self, ev: Evaluation) -> None:
        with self._l:
            self._process_enqueue(ev, "")

    def enqueue_all(self, evals: Dict[str, Tuple[Evaluation, str]]) -> None:
        """{eval_id: (eval, token)} — token set when reblocking."""
        with self._l:
            for ev, token in evals.values():
                self._process_enqueue(ev, token)

    def _process_enqueue(self, ev: Evaluation, token: str) -> None:
        if not self._enabled:
            return
        # flight-recorder anchor (ISSUE 9): FIRST broker entry, kept
        # across blocked/delayed parking and requeues — dequeue derives
        # broker_wait_s from it, so an eval that sat on the per-job
        # blocked heap or the delayed heap shows that time in its span
        # tree (queue_wait_s below stays READY-queue-only: it feeds
        # the governor's latency reservoir and must keep its meaning)
        if getattr(ev, "_entered_broker_t", None) is None:
            ev._entered_broker_t = time.monotonic()
        if ev.id in self._evals:
            if token == "":
                return
            unack = self._unack.get(ev.id)
            if unack is not None and unack.token == token:
                self._requeue[token] = ev
            return
        self._evals[ev.id] = 0

        if ev.wait_s > 0:
            self._process_waiting(ev)
            return
        if ev.wait_until > 0:
            self._delay_seq += 1
            q = (self._delayed_core_q if ev.type == JOB_TYPE_CORE
                 else self._delayed)
            heapq.heappush(q, (ev.wait_until, self._delay_seq, ev))
            self.stats.total_waiting += 1
            self._reset_delay_timer()
            return
        if self._admission_defer(ev):
            return
        self._enqueue_locked(ev, ev.type)

    def _admission_defer(self, ev: Evaluation) -> bool:
        """Backpressure shed: while the governor's pressure gauge is
        over its watermark, fresh (non-core) enqueues park on the
        delayed heap for admission_delay_s instead of joining the
        ready queue; the pop cycle re-tests the gauge, so work admits
        the moment it clears. Bounded memory (the delayed heap) traded
        for bounded queue depth and dispatch latency — the nack/requeue
        analog of the reference's plan-apply admission control.
        total_shed counts these shed DECISIONS once per eval; the pop
        cycle's re-parks don't come back through here."""
        fn = self.pressure_fn
        if fn is None or ev.type == JOB_TYPE_CORE:
            return False
        try:
            if not fn():
                return False
        except Exception:       # pragma: no cover — defensive
            return False
        self.stats.total_shed += 1
        self._delay_seq += 1
        heapq.heappush(self._delayed,
                       (time.time() + self.admission_delay_s,
                        self._delay_seq, ev))
        self.stats.total_waiting += 1
        self._reset_delay_timer()
        return True

    def _process_waiting(self, ev: Evaluation) -> None:
        timer = threading.Timer(ev.wait_s, self._enqueue_waiting, args=(ev,))
        timer.daemon = True
        timer.start()
        self._time_wait[ev.id] = timer
        self.stats.total_waiting += 1

    def _enqueue_waiting(self, ev: Evaluation) -> None:
        with self._l:
            self._time_wait.pop(ev.id, None)
            self.stats.total_waiting -= 1
            self._enqueue_locked(ev, ev.type)

    def _arm_delay_timer(self, delay: float) -> None:
        if self._delay_timer:
            self._delay_timer.cancel()
        self._delay_timer = threading.Timer(delay, self._pop_delayed)
        self._delay_timer.daemon = True
        self._delay_timer_at = time.time() + delay
        self._delay_timer.start()

    def _reset_delay_timer(self) -> None:
        nxt = self._delayed[0][0] if self._delayed else None
        if self._delayed_core_q and \
                (nxt is None or self._delayed_core_q[0][0] < nxt):
            nxt = self._delayed_core_q[0][0]
        if nxt is None:
            if self._delay_timer:
                self._delay_timer.cancel()
                self._delay_timer = None
            return
        # an armed timer already fires at/before the heap head: leave
        # it — re-arming here would cancel and spawn a fresh OS timer
        # thread per shed enqueue, thread churn proportional to the
        # very overload admission control is relieving
        if self._delay_timer is not None and self._delay_timer_at <= nxt:
            return
        self._arm_delay_timer(max(0.0, nxt - time.time()))

    def _pop_delayed(self) -> None:
        with self._l:
            # we ARE the fired timer: forget it so _reset_delay_timer
            # re-arms instead of trusting a dead timer's deadline
            self._delay_timer = None
            now = time.time()
            # core evals admit on schedule regardless of pressure —
            # GC work keeps the overloaded server healthy
            while self._delayed_core_q and \
                    self._delayed_core_q[0][0] <= now:
                _, _, ev = heapq.heappop(self._delayed_core_q)
                self.stats.total_waiting -= 1
                self._enqueue_locked(ev, ev.type)
            # pressure is tested ONCE per cycle: under sustained
            # pressure due non-core evals simply stay parked — the
            # heap is untouched, so a 50k-deep parked set costs one
            # function call per admission window, not 50k heap pops +
            # pushes inside the broker lock. When the gauge clears,
            # everything due admits in one batch
            pressured = False
            fn = self.pressure_fn
            if fn is not None and self._delayed:
                try:
                    pressured = bool(fn())
                except Exception:   # pragma: no cover — defensive
                    pressured = False
            if pressured:
                delay = self.admission_delay_s
                if self._delayed_core_q:
                    delay = min(delay, max(
                        0.0, self._delayed_core_q[0][0] - now))
                self._arm_delay_timer(delay)
                return
            while self._delayed and self._delayed[0][0] <= now:
                _, _, ev = heapq.heappop(self._delayed)
                self.stats.total_waiting -= 1
                self._enqueue_locked(ev, ev.type)
            self._reset_delay_timer()

    def _enqueue_locked(self, ev: Evaluation, queue: str) -> None:
        if not self._enabled:
            return
        key = (ev.namespace, ev.job_id)
        pending = self._job_evals.get(key, "")
        if pending == "":
            self._job_evals[key] = ev.id
        elif pending != ev.id:
            blocked = self._blocked.setdefault(key, _PQ())
            blocked.push(ev)
            self.stats.total_blocked += 1
            return
        q = self._ready.setdefault(queue, _PQ())
        # queue-wait attribution (ISSUE 7 satellite): stamp READY-queue
        # entry so dequeue can report how long the eval waited — the
        # workers fold it into the sampled p99, where a backed-up
        # queue was previously invisible
        ev._brokered_t = time.monotonic()
        q.push(ev)
        self.stats.total_ready += 1
        self._l.notify_all()

    # -- dequeue -------------------------------------------------------
    def dequeue(self, schedulers: List[str],
                timeout_s: Optional[float] = None
                ) -> Tuple[Optional[Evaluation], str]:
        deadline = (time.monotonic() + timeout_s) if timeout_s is not None else None
        with self._l:
            while True:
                best_queue = None
                best = None
                for sched in schedulers:
                    q = self._ready.get(sched)
                    if q is None or len(q) == 0:
                        continue
                    head = q.peek()
                    if best is None or (-head.priority, head.create_index) < \
                            (-best.priority, best.create_index):
                        best = head
                        best_queue = sched
                if best is not None:
                    return self._dequeue_for_sched(best_queue)
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None, ""
                self._l.wait(remaining if remaining is not None else 1.0)
                if deadline is None and not self._enabled:
                    return None, ""

    def _dequeue_for_sched(self, sched: str) -> Tuple[Evaluation, str]:
        q = self._ready[sched]
        ev = q.pop()
        now = time.monotonic()
        ev.queue_wait_s = max(
            0.0, now - getattr(ev, "_brokered_t", now))
        ev.broker_wait_s = max(
            ev.queue_wait_s,
            now - (getattr(ev, "_entered_broker_t", None) or now))
        token = generate_uuid()
        timer = threading.Timer(self.nack_timeout_s, self.nack,
                                args=(ev.id, token))
        timer.daemon = True
        timer.start()
        self._unack[ev.id] = _Unack(ev, token, timer)
        self._evals[ev.id] = self._evals.get(ev.id, 0) + 1
        self.stats.total_ready -= 1
        self.stats.total_unacked += 1
        return ev, token

    # -- ack/nack ------------------------------------------------------
    def outstanding(self, eval_id: str) -> Optional[str]:
        with self._l:
            unack = self._unack.get(eval_id)
            return unack.token if unack else None

    def ack(self, eval_id: str, token: str) -> None:
        with self._l:
            try:
                unack = self._unack.get(eval_id)
                if unack is None:
                    raise KeyError("Evaluation ID not found")
                if unack.token != token:
                    raise ValueError("Token does not match for Evaluation ID")
                unack.nack_timer.cancel()
                self.stats.total_unacked -= 1
                del self._unack[eval_id]
                self._evals.pop(eval_id, None)
                key = (unack.eval.namespace, unack.eval.job_id)
                self._job_evals.pop(key, None)
                blocked = self._blocked.get(key)
                if blocked is not None and len(blocked):
                    ev = blocked.pop()
                    if not len(blocked):
                        del self._blocked[key]
                    self.stats.total_blocked -= 1
                    self._enqueue_locked(ev, ev.type)
                requeued = self._requeue.pop(token, None)
                if requeued is not None:
                    self._process_enqueue(requeued, "")
            finally:
                self._requeue.pop(token, None)

    def nack(self, eval_id: str, token: str,
             delay_s: Optional[float] = None) -> None:
        """Return an outstanding eval to READY. `delay_s` overrides the
        delivery-count backoff: the scheduler plane's lease sweeper
        (ISSUE 16) passes 0.0 when a remote FOLLOWER died holding the
        eval — the eval did nothing wrong and should redeliver
        immediately, not serve the failed-attempt penalty."""
        with self._l:
            self._requeue.pop(token, None)
            unack = self._unack.get(eval_id)
            if unack is None or unack.token != token:
                return
            unack.nack_timer.cancel()
            del self._unack[eval_id]
            self.stats.total_unacked -= 1
            dequeues = self._evals.get(eval_id, 0)
            if dequeues >= self.delivery_limit:
                self._enqueue_locked(unack.eval, FAILED_QUEUE)
            else:
                ev = unack.eval
                ev.wait_s = (self._nack_reenqueue_delay(dequeues)
                             if delay_s is None else delay_s)
                if ev.wait_s > 0:
                    self._process_waiting(ev)
                else:
                    self._enqueue_locked(ev, ev.type)

    def _nack_reenqueue_delay(self, prev_dequeues: int) -> float:
        if prev_dequeues <= 0:
            return 0.0
        if prev_dequeues == 1:
            return self.initial_nack_delay_s
        return (prev_dequeues - 1) * self.subsequent_nack_delay_s
