"""SWIM-style peer failure detection.

Reference: nomad/serf.go + hashicorp/serf's SWIM implementation —
every server probes random peers directly, falls back to indirect
probes through other members, moves unresponsive peers through
SUSPECT to FAILED, and the leader's autopilot consumes the verdicts.
The round-4 design derived liveness solely from the leader's
replication contact clock; this detector makes failure detection a
peer-to-peer property: ANY member can detect and report a failed
server, and the leader removes it after verifying it can't reach the
target either — no dependence on the replication threads
(VERDICT r4 item 8).

Simplifications vs full SWIM, at cluster sizes the reference targets
(3-9 servers): verdict dissemination is a direct report to the leader
(Server.ReportFailed) instead of gossip piggybacking, and refutation
is implicit — a reachable target answers the leader's verification
probe and the report is dropped.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Dict, List, Optional

from ..chaos import faults as chaos_faults
from ..rpc.codec import RpcRefused

LOG = logging.getLogger("nomad_tpu.swim")

PROBE_INTERVAL_S = 0.5
PROBE_TIMEOUT_S = 0.5
SUSPICION_S = 1.5
INDIRECT_K = 2

STATE_ALIVE = "alive"
STATE_SUSPECT = "suspect"
STATE_FAILED = "failed"


class SwimDetector:
    def __init__(self, server,
                 probe_interval_s: float = PROBE_INTERVAL_S,
                 probe_timeout_s: float = PROBE_TIMEOUT_S,
                 suspicion_s: float = SUSPICION_S,
                 indirect_k: int = INDIRECT_K):
        self.server = server
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.suspicion_s = suspicion_s
        self.indirect_k = indirect_k
        # addr -> {"state", "suspect_since", "last_ack"}
        self.states: Dict[str, Dict] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._probe_order: List[str] = []
        self.stats = {"probes": 0, "indirect": 0, "reported": 0}

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True, name="swim")
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    # -- probing -------------------------------------------------------
    def _members(self) -> List[str]:
        raft = self.server.raft
        if raft is None:
            return []
        members = self.server.store.server_members() or \
            [raft.self_addr] + list(raft.peers)
        return [m for m in members if m != raft.self_addr]

    def live_members(self) -> List[str]:
        """Members not currently under a FAILED verdict — the
        scheduler plane's re-homing directory (ISSUE 16): a follower
        hunting for the new leader skips peers this detector already
        condemned instead of eating their dial timeouts."""
        return [m for m in self._members()
                if self.states.get(m, {}).get("state") != STATE_FAILED]

    def _ping(self, addr: str) -> bool:
        if chaos_faults.ACTIVE and \
                chaos_faults.fire("swim.probe", target=addr,
                                  via=""):
            # chaos hook (ISSUE 15): an installed partition fault
            # fails probes to its victim set — the network is down,
            # the victim process is not
            return False
        from ..rpc.client import RpcClient
        try:
            c = RpcClient(addr, dial_timeout_s=self.probe_timeout_s)
            try:
                c.call("Status.Ping", {},
                       timeout_s=self.probe_timeout_s)
                return True
            finally:
                c.close()
        except Exception:
            return False

    def _indirect_ping(self, via: str, target: str) -> bool:
        if chaos_faults.ACTIVE and \
                chaos_faults.fire("swim.probe", target=target, via=via):
            # a partitioned victim is unreachable via relays too: the
            # ping-req's last hop crosses the same cut
            return False
        from ..rpc.client import RpcClient
        try:
            c = RpcClient(via, dial_timeout_s=self.probe_timeout_s)
            try:
                res = c.call("Server.IndirectPing", {"target": target},
                             timeout_s=self.probe_timeout_s * 3)
                return bool(res.get("ok"))
            finally:
                c.close()
        except Exception:
            return False

    def probe_for_peer(self, target: str) -> bool:
        """Serve another member's indirect probe (SWIM ping-req)."""
        return self._ping(target)

    def _next_target(self, members: List[str]) -> Optional[str]:
        """Round-robin over a shuffled member ring (SWIM's probe
        schedule: every member probed once per cycle, random order)."""
        self._probe_order = [m for m in self._probe_order
                             if m in members]
        if not self._probe_order:
            self._probe_order = list(members)
            random.shuffle(self._probe_order)
        return self._probe_order.pop() if self._probe_order else None

    def _loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            try:
                self._tick()
            except RpcRefused as e:
                # a suspect/dead raft write hit a raft node that has
                # already stopped (staggered teardown, mid-transfer
                # fencing) — a protocol refusal, not a probe fault
                LOG.debug("swim tick refused: %s", e)
            except Exception:       # pragma: no cover — keep probing
                LOG.exception("swim tick failed")

    def _tick(self) -> None:
        members = self._members()
        for gone in set(self.states) - set(members):
            self.states.pop(gone, None)
        target = self._next_target(members)
        if target is None:
            return
        self.stats["probes"] += 1
        now = time.monotonic()
        st = self.states.setdefault(
            target, {"state": STATE_ALIVE, "suspect_since": 0.0,
                     "last_ack": now})
        if self._ping(target):
            st.update(state=STATE_ALIVE, suspect_since=0.0,
                      last_ack=now)
            return
        # direct probe failed: try K indirect routes (SWIM ping-req)
        others = [m for m in members if m != target]
        random.shuffle(others)
        for via in others[:self.indirect_k]:
            self.stats["indirect"] += 1
            if self._indirect_ping(via, target):
                st.update(state=STATE_ALIVE, suspect_since=0.0,
                          last_ack=now)
                return
        if st["state"] == STATE_ALIVE:
            st.update(state=STATE_SUSPECT, suspect_since=now)
            LOG.warning("swim: %s is SUSPECT", target)
            return
        if st["state"] == STATE_SUSPECT and \
                now - st["suspect_since"] >= self.suspicion_s:
            st["state"] = STATE_FAILED
            LOG.warning("swim: %s is FAILED, reporting", target)
        if st["state"] == STATE_FAILED:
            self._report(target)

    def _report(self, target: str) -> None:
        """Deliver the verdict to the leader (repeats every probe cycle
        until the membership change lands)."""
        self.stats["reported"] += 1
        server = self.server
        raft = server.raft
        if raft is not None and raft.is_leader():
            server.handle_peer_failure_report(target,
                                              reporter=raft.self_addr)
            return
        from ..rpc.client import RpcClient
        leader = getattr(raft, "leader_addr", None) if raft else None
        candidates = ([leader] if leader else []) + \
            [m for m in self._members() if m != target]
        for addr in candidates:
            try:
                c = RpcClient(addr, dial_timeout_s=self.probe_timeout_s)
                try:
                    c.call("Server.ReportFailed",
                           {"addr": target,
                            "reporter": raft.self_addr if raft else ""},
                           timeout_s=2.0)
                    return
                finally:
                    c.close()
            except Exception:
                continue
