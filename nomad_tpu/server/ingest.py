"""IngestGateway: the write-side twin of the r11 micro-batch gateway.

The read/schedule side is batched end-to-end (r9 plan group commit,
r11 micro-batch dispatch, r21 compiled feasibility), but before this
every write walked in alone: HTTP register -> decode -> one raft entry
-> one store transaction -> one event flush, per object. This gateway
coalesces the three north-bound write kinds — job registers, client
alloc-status updates, and desired-transition writes — so that writes
arriving while a raft apply is in flight PARK and land together as ONE
`ingest_batch` raft entry, ONE store transaction
(`upsert_jobs_batch` / `update_allocs_from_client_batch`), and ONE
event flush, with per-request futures demultiplexed back to each
submitter exactly like the r9 plan applier's group commit.

Trigger discipline mirrors the MicroBatchGateway (worker.py):

  - drain:     entries that parked while the previous batch's raft
               apply was in flight fire immediately on its completion —
               the in-flight apply WAS the batching window (the same
               self-clocking the plan applier gets from its queue);
  - occupancy: the window fills to `ingest_batch_max` -> fire early;
  - immediate: nothing else is streaming in -> a lone write never
               waits (idle-path latency unchanged from pre-gateway);
  - deadline:  while a burst is streaming, the oldest waiter bounds
               the wait at the (governor-scaled) window.

Governor coupling inverts the read side's: a deep ingest queue means
the committer is saturated and window-waiting only adds latency (drain
already self-clocks batch formation), so the
`governor_ingest_queue_high` reclaim HALVES the window and a clean
streak (GROUP_RECOVER_CLEAN batches under watermark) re-widens it —
the r9 shrink/recover idiom pointed at admission. `check_admission`
sheds with 429/Retry-After BEFORE body decode when queue depth or
queued bytes cross the watermark.

Bisection: `NOMAD_TPU_INGEST_BATCH=0` (or `ingest_window_us<0`) stops
the gateway from being constructed at all — every write takes the
unchanged one-entry-per-object path. Single-entry batches also take
the unchanged singleton raft entries, so an idle server's WAL is
bit-identical with the gateway on or off.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Optional

from .eval_broker import AdmissionOverloadError
from .plan_applier import GROUP_RECOVER_CLEAN, fail_futures
from ..utils import metrics
from ..utils.locks import make_condition, make_lock

INGEST_ENV = "NOMAD_TPU_INGEST_BATCH"

# the three write kinds that may coalesce; each value is the singleton
# raft msg_type the entry demotes to when it commits alone
INGEST_KINDS = ("job_register", "alloc_client_update",
                "alloc_desired_transition")

# window scale floor under governor reclaim: 1/8th of the configured
# window — below that the deadline trigger is indistinguishable from
# immediate and shrinking further just burns reclaim rounds
SCALE_MIN = 0.125

# process-wide accounting (the GROUP_STATS idiom): bench.py reads this
# after a run so batching is attributable across every server the
# bench spun up. Written only by gateway threads; racy reads are fine.
INGEST_STATS: Dict[str, int] = {
    "batches": 0, "writes": 0, "coalesced": 0, "shed": 0, "max_size": 0,
}


def ingest_batch_enabled() -> bool:
    """The bisection escape hatch: NOMAD_TPU_INGEST_BATCH=0 keeps the
    gateway from being constructed — one raft entry per write."""
    return os.environ.get(INGEST_ENV, "1") not in ("0", "off", "no")


class _Entry:
    __slots__ = ("kind", "payload", "future", "arrival_t", "nbytes")

    def __init__(self, kind: str, payload: dict, nbytes: int):
        self.kind = kind
        self.payload = payload
        self.future: Future = Future()
        self.arrival_t = time.monotonic()
        self.nbytes = nbytes


class IngestGateway:
    # commit-latency reservoir bound: enough for a p99 over the bench
    # storm without unbounded growth
    LAT_WINDOW = 4096

    def __init__(self, server, batch_max: int = 64,
                 window_us: float = 200.0, queue_high: int = 256):
        self.server = server          # provides .raft_apply()
        self.batch_max = max(1, int(batch_max))
        self.base_window_s = max(float(window_us), 0.0) / 1e6
        self.queue_high = max(1, int(queue_high))
        # queued-bytes watermark derived from depth: watermark depth x
        # a conservative 64 KiB mean body keeps a few huge bulk bodies
        # from hiding behind a shallow queue
        self.queue_bytes_high = self.queue_high * 64 * 1024
        self._cv = make_condition()
        self._pending: List[_Entry] = []
        self._pending_bytes = 0
        self._stopped = False
        # entries present at loop-top right after a batch landed parked
        # during its raft apply -> drain trigger
        self._drain_ready = False
        # governor reclaim state (r9 shrink/recover idiom, inverted:
        # pressure SHRINKS the window, clean batches re-widen it)
        self._scale = 1.0
        self._clean_batches = 0
        self._lat_l = make_lock()
        self._lat: deque = deque(maxlen=self.LAT_WINDOW)   # seconds/write
        # counters are += read-modify-writes from the gateway thread
        # (_note_batch), request threads (submit_async, under _cv), and
        # the shed path (check_admission, which deliberately avoids
        # _cv) — no shared lock between them, so they get their own
        self._stats_l = make_lock()
        # nomad-lint: guarded-by[_stats_l]
        self.stats: Dict[str, float] = {
            "requests": 0, "batches": 0, "entries_sum": 0,
            "coalesced_writes": 0, "shed": 0,
            "immediate_dispatches": 0, "occupancy_dispatches": 0,
            "drain_dispatches": 0, "deadline_dispatches": 0,
            "wait_s_sum": 0.0,
        }
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ingest-gateway")
        self._thread.start()

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        if self._thread:
            self._thread.join(timeout=5)
        with self._cv:
            leftovers, self._pending = self._pending, []
            self._pending_bytes = 0
        fail_futures([(e.future, None) for e in leftovers],
                     RuntimeError("ingest gateway stopped"))

    # -- gauges / governor hooks ---------------------------------------
    def queue_depth(self) -> int:
        return len(self._pending)

    def queue_bytes(self) -> int:
        return self._pending_bytes

    def window_us(self) -> float:
        return self.base_window_s * self._scale * 1e6

    def mean_batch_size(self) -> float:
        b = self.stats["batches"]
        return self.stats["entries_sum"] / b if b else 0.0

    def write_p99_ms(self) -> float:
        with self._lat_l:
            if not self._lat:
                return 0.0
            xs = sorted(self._lat)
        return xs[min(len(xs) - 1, int(len(xs) * 0.99))] * 1000.0

    def shrink_window(self) -> dict:
        """Governor reclaim for `governor_ingest_queue_high`: a deep
        queue means the committer is the bottleneck and window-waiting
        only adds latency (the drain trigger already self-clocks batch
        formation) — halve the window. Recovery is automatic
        (_note_batch re-widens after a clean streak)."""
        was = self._scale
        self._scale = max(SCALE_MIN, self._scale / 2.0)
        self._clean_batches = 0
        return {"ingest_window_us": round(self.window_us(), 1),
                "was_us": round(self.base_window_s * was * 1e6, 1)}

    def _note_batch(self, size: int, wait_s: float, trigger: str) -> None:
        with self._stats_l:
            self.stats["batches"] += 1
            self.stats["entries_sum"] += size
            self.stats[f"{trigger}_dispatches"] += 1
            self.stats["wait_s_sum"] += wait_s
            if size > 1:
                # every request beyond the first shared a raft entry
                # with a neighbor — the headline coalescing gauge
                self.stats["coalesced_writes"] += size - 1
        if size > 1:
            INGEST_STATS["coalesced"] += size - 1
        INGEST_STATS["batches"] += 1
        INGEST_STATS["writes"] += size
        if size > INGEST_STATS["max_size"]:
            INGEST_STATS["max_size"] = size
        # counter totals the telemetry ring turns into writes/s rates
        # (`nomad operator top`'s write block)
        metrics.incr_counter("nomad.ingest.writes", size)
        metrics.incr_counter("nomad.ingest.batches")
        if len(self._pending) * 4 < self.queue_high:
            self._clean_batches += 1
            if self._scale < 1.0 and \
                    self._clean_batches >= GROUP_RECOVER_CLEAN:
                self._clean_batches = 0
                self._scale = min(1.0, self._scale * 2.0)
        else:
            self._clean_batches = 0

    # -- admission (runs BEFORE body decode) ---------------------------
    def check_admission(self, bytes_hint: int = 0) -> None:
        """Shed valve for the real ingest backlog: refuse new writes at
        the edge (429 + Retry-After) when the queue has crossed its
        depth or byte watermark. Called with the Content-Length hint
        BEFORE the body is decoded, so an overloaded server never pays
        msgpack/model materialization for work it is about to refuse."""
        depth = len(self._pending)
        qbytes = self._pending_bytes + max(0, int(bytes_hint))
        over_depth = depth >= self.queue_high
        over_bytes = qbytes > self.queue_bytes_high
        if not over_depth and not over_bytes:
            return
        with self._stats_l:
            self.stats["shed"] += 1
        INGEST_STATS["shed"] += 1
        metrics.incr_counter("nomad.ingest.shed")
        # back-off scales with overshoot (capped 8x, floor 1s) — the
        # broker valve's Retry-After discipline
        ratio = max(depth / self.queue_high, qbytes / self.queue_bytes_high)
        retry = max(1.0, min(ratio, 8.0))
        what = (f"{depth} queued writes (watermark {self.queue_high})"
                if over_depth else
                f"{qbytes} queued bytes (watermark {self.queue_bytes_high})")
        raise AdmissionOverloadError(
            f"ingest gateway overloaded: {what}; "
            f"retry after {retry:.0f}s", retry_after_s=retry)

    # -- submission -----------------------------------------------------
    def submit_async(self, kind: str, payload: dict,
                     nbytes: int = 0) -> Future:
        """Park one write for the next batch. The future resolves to
        the raft index its batch (or singleton entry) committed at."""
        if kind not in INGEST_KINDS:
            raise ValueError(f"unknown ingest kind {kind!r}")
        entry = _Entry(kind, payload, nbytes)
        with self._cv:
            if self._stopped:
                raise RuntimeError("ingest gateway stopped")
            started = self._thread is not None
            if started:
                self._pending.append(entry)
                self._pending_bytes += entry.nbytes
            with self._stats_l:
                self.stats["requests"] += 1
            if started:
                self._cv.notify_all()
        if not started:
            # gateway thread not running (library/test servers that
            # never call Server.start()): the caller thread commits its
            # own singleton — the same per-kind raft entry the loop's
            # immediate trigger emits, so nothing parks forever
            self._commit([entry], 0.0, "immediate")
        return entry.future

    def submit(self, kind: str, payload: dict, nbytes: int = 0) -> int:
        return self.submit_async(kind, payload, nbytes).result()

    # -- the gateway loop ----------------------------------------------
    def _streaming(self) -> bool:
        """More than one waiter, or one that just arrived while another
        batch was landing — a burst worth a window wait."""
        return len(self._pending) > 1

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._stopped:
                    self._drain_ready = False
                    self._cv.wait(0.2)
                if self._stopped:
                    return
                trigger = None
                if len(self._pending) >= self.batch_max:
                    trigger = "occupancy"
                elif self._drain_ready:
                    # these parked while the previous apply was in
                    # flight: the apply WAS their window
                    trigger = "drain"
                elif not self._streaming():
                    trigger = "immediate"
                else:
                    # burst streaming in: bound the wait by the oldest
                    # waiter + the governor-scaled window
                    window = self.base_window_s * self._scale
                    while True:
                        if len(self._pending) >= self.batch_max:
                            trigger = "occupancy"
                            break
                        oldest = self._pending[0].arrival_t
                        remaining = oldest + window - time.monotonic()
                        if remaining <= 0:
                            trigger = "deadline"
                            break
                        self._cv.wait(remaining)
                        if self._stopped:
                            return
                batch = self._pending[:self.batch_max]
                del self._pending[:len(batch)]
                self._pending_bytes -= sum(e.nbytes for e in batch)
                now = time.monotonic()
                wait_s = sum(now - e.arrival_t for e in batch)
            self._commit(batch, wait_s, trigger)
            with self._cv:
                # anything queued right now parked during the apply
                self._drain_ready = bool(self._pending)

    def _commit(self, batch: List[_Entry], wait_s: float,
                trigger: str) -> None:
        try:
            if len(batch) == 1:
                # singleton fast path: the unchanged per-kind raft
                # entry, so an idle server's WAL is bit-identical with
                # the gateway off (the r9 singleton-fallback idiom)
                e = batch[0]
                index = self.server.raft_apply(e.kind, e.payload)
            else:
                entries = [dict(e.payload, kind=e.kind) for e in batch]
                index = self.server.raft_apply(
                    "ingest_batch", {"entries": entries})
        except Exception as exc:
            fail_futures([(e.future, None) for e in batch], exc)
            return
        finally:
            self._note_batch(len(batch), wait_s, trigger)
        # full write latency as each submitter saw it: park + window +
        # apply — the `ingest.write_p99_ms` source
        t1 = time.monotonic()
        with self._lat_l:
            for e in batch:
                self._lat.append(t1 - e.arrival_t)
        # demultiplex: every submitter gets the group's commit index,
        # in submission order (the r9 committer idiom)
        for e in batch:
            if not e.future.done():
                e.future.set_result(index)
