"""Deployment watcher — drives rolling updates to a verdict.

Watches every active deployment and, on allocation-health changes:
auto-promotes canary deployments once all canaries are healthy
(deployment_watcher.go), fails deployments with unhealthy allocations
and auto-reverts the job to its latest stable version
(deployments_watcher.go FailDeployment + Job rollback), enforces
per-task-group progress deadlines, and marks deployments successful
(+ the job version stable) when every group reaches its desired healthy
count.

Reference semantics: nomad/deploymentwatcher/deployments_watcher.go
(Watcher:75, watchDeployments), deployment_watcher.go (watch:345,
autoPromoteDeployment:505, FailDeployment:300, progress deadline at
watch:370-430, setDeploymentStatus) and state_store.go
UpdateDeploymentPromotion / UpdateJobStability.

The structural translation: instead of one goroutine per deployment,
a single thread re-evaluates all active deployments on every state-store
index change (the store's blocking watch is the getAllocsCh analog) plus
a short tick for deadline expiry. Per-deployment progress deadlines are
tracked in memory and re-derived after leader restart — deadlines
restart on leadership change, matching the reference's behavior of
recreating watchers from state.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from ..models import Evaluation, EVAL_STATUS_PENDING
from ..models.deployment import (
    Deployment, DeploymentStatusUpdate,
    DEPLOYMENT_STATUS_FAILED, DEPLOYMENT_STATUS_PAUSED,
    DEPLOYMENT_STATUS_RUNNING, DEPLOYMENT_STATUS_SUCCESSFUL,
    DESC_FAILED_ALLOCATIONS, DESC_FAILED_BY_USER, DESC_PROGRESS_DEADLINE,
    DESC_SUCCESSFUL,
)
from ..models.evaluation import TRIGGER_DEPLOYMENT_WATCHER

LOG = logging.getLogger("nomad_tpu.deployments")


class DeploymentsWatcher:
    """Leader-only service (enabled in establishLeadership, leader.go:222)."""

    TICK_S = 0.25

    def __init__(self, server):
        self.server = server
        self._enabled = False
        self._gen = 0   # generation token: stale threads see a bump and exit
        self._thread: Optional[threading.Thread] = None
        # deployment_id -> tg name -> {"healthy": int, "deadline": float}
        self._progress: Dict[str, Dict[str, dict]] = {}

    def set_enabled(self, enabled: bool) -> None:
        if enabled and not self._enabled:
            self._enabled = True
            self._gen += 1
            # deadlines restart on (re-)election: REBIND instead of
            # clear() — a stale-generation thread caught mid-tick past
            # its _live check keeps mutating the old (now garbage)
            # dict instead of repopulating the fresh one
            self._progress = {}
            self._thread = threading.Thread(target=self._run,
                                            args=(self._gen,), daemon=True,
                                            name="deployment-watcher")
            self._thread.start()
        elif not enabled:
            self._enabled = False

    def _live(self, gen: int) -> bool:
        return self._enabled and gen == self._gen

    # -- watch loop ----------------------------------------------------
    def _run(self, gen: int) -> None:
        while self._live(gen):
            snap = self.server.store.snapshot()
            try:
                self._scan(snap, gen)
            except Exception:
                LOG.exception("deployment scan failed")
            # wake on any state change, or tick for deadline expiry
            self.server.store.block_min_index(snap.latest_index() + 1,
                                              timeout_s=self.TICK_S)

    def _scan(self, snap, gen: int) -> None:
        active = set()
        for d in snap.deployments():
            if not self._live(gen):
                return  # stale thread must not raft-apply as non-leader
            if not d.active():
                continue
            active.add(d.id)
            try:
                self._evaluate(snap, d)
            except Exception:
                LOG.exception("evaluating deployment %s", d.id[:8])
        for did in list(self._progress):
            if did not in active:
                del self._progress[did]

    def _evaluate(self, snap, d: Deployment) -> None:
        if d.status == DEPLOYMENT_STATUS_PAUSED:
            return
        job = snap.job_by_id(d.namespace, d.job_id)
        if job is None or job.stopped() or job.version != d.job_version:
            # superseded/stopped jobs are cancelled by the reconciler's
            # deployment_updates on its next pass — nothing to do here
            return
        allocs = snap.allocs_by_deployment(d.id)

        # 1. failure: any alloc reported unhealthy (watch:370)
        if any(a.deployment_status is not None
               and a.deployment_status.is_unhealthy() for a in allocs):
            self.server.fail_deployment(d.id, desc=DESC_FAILED_ALLOCATIONS)
            return

        # 2. progress deadline per task group (watch:390-430)
        now = time.time()
        track = self._progress.setdefault(d.id, {})
        for name, state in d.task_groups.items():
            rec = track.get(name)
            if rec is None or state.healthy_allocs > rec["healthy"]:
                track[name] = {"healthy": state.healthy_allocs,
                               "deadline": now + state.progress_deadline_s}
            elif (state.progress_deadline_s > 0 and now > rec["deadline"]
                  and state.healthy_allocs < state.desired_total):
                self.server.fail_deployment(d.id, desc=DESC_PROGRESS_DEADLINE)
                return

        # 3. auto-promotion (autoPromoteDeployment:505)
        if d.requires_promotion():
            if d.has_auto_promote() \
                    and not unhealthy_canary_groups(snap, d):
                try:
                    self.server.promote_deployment(d.id)
                except (ValueError, KeyError) as e:
                    LOG.debug("auto-promote %s: %s", d.id[:8], e)
            return  # unpromoted deployments can't complete

        # 4. success: every group at desired healthy count
        if d.task_groups and all(s.healthy_allocs >= s.desired_total
                                 for s in d.task_groups.values()):
            self._succeed(d)

    def _succeed(self, d: Deployment) -> None:
        update = DeploymentStatusUpdate(
            deployment_id=d.id, status=DEPLOYMENT_STATUS_SUCCESSFUL,
            status_description=DESC_SUCCESSFUL)
        # one raft entry: a crash must never leave the deployment
        # successful without the version flagged stable (the auto-revert
        # target), so stability rides in the same apply
        self.server.raft_apply(
            "deployment_status_update",
            dict(update=update, evals=[],
                 stability=dict(namespace=d.namespace, job_id=d.job_id,
                                version=d.job_version, stable=True)))
        self._progress.pop(d.id, None)
        LOG.info("deployment %s for %s v%d successful",
                 d.id[:8], d.job_id, d.job_version)


# -- server-side RPC surface (Deployment.Promote/Fail/Pause endpoints) --

def unhealthy_canary_groups(snap, d: Deployment,
                            groups: Optional[List[str]] = None) -> List[str]:
    """Task groups whose desired canaries are not all placed+healthy.
    Shared by auto-promote and the Promote RPC so both gates agree."""
    by_id = {a.id: a for a in snap.allocs_by_deployment(d.id)}
    bad = []
    for name, state in d.task_groups.items():
        if state.desired_canaries == 0 or (groups and name not in groups):
            continue
        healthy = sum(
            1 for cid in state.placed_canaries
            if (a := by_id.get(cid)) is not None
            and a.deployment_status is not None
            and a.deployment_status.is_healthy())
        if healthy < state.desired_canaries:
            bad.append(name)
    return bad


def make_watcher_eval(d: Deployment, job) -> Evaluation:
    return Evaluation(
        namespace=d.namespace,
        priority=job.priority if job is not None else 50,
        type=job.type if job is not None else "service",
        triggered_by=TRIGGER_DEPLOYMENT_WATCHER,
        job_id=d.job_id,
        deployment_id=d.id,
        status=EVAL_STATUS_PENDING)


def promote_deployment(server, deployment_id: str,
                       groups: Optional[List[str]] = None) -> Evaluation:
    """Deployment.Promote (deployment_watcher.go PromoteDeployment:255):
    validate canary health, flip promoted, emit a reconcile eval."""
    d = server.store.deployment_by_id(deployment_id)
    if d is None:
        raise KeyError(f"deployment {deployment_id} not found")
    if not d.active():
        raise ValueError(f"deployment {deployment_id} has terminal status "
                         f"{d.status}")
    if not d.requires_promotion():
        raise ValueError("deployment has nothing to promote")
    bad = unhealthy_canary_groups(server.store.snapshot(), d, groups)
    if bad:
        raise ValueError(
            f"task groups {bad} do not have all canaries placed and "
            f"healthy canaries — promotion requires all canaries healthy")
    job = server.store.job_by_id(d.namespace, d.job_id)
    ev = make_watcher_eval(d, job)
    server.raft_apply("deployment_promotion",
                      dict(deployment_id=deployment_id, groups=groups,
                           evals=[ev]))
    return ev


def fail_deployment(server, deployment_id: str,
                    desc: str = DESC_FAILED_BY_USER) -> Optional[Evaluation]:
    """Deployment.Fail: mark failed; if any group has auto_revert, roll
    the job back to its latest stable version
    (deployment_watcher.go FailDeployment:300 + latestStableJob:760)."""
    d = server.store.deployment_by_id(deployment_id)
    if d is None:
        raise KeyError(f"deployment {deployment_id} not found")
    if not d.active():
        raise ValueError(f"deployment {deployment_id} has terminal status "
                         f"{d.status}")
    job = server.store.job_by_id(d.namespace, d.job_id)
    rollback = None
    if any(s.auto_revert for s in d.task_groups.values()):
        rollback = latest_stable_job(server.store, d)
        if rollback is not None and job is not None \
                and not job.specchanged(rollback):
            rollback = None  # stable spec == failed spec; don't loop
    if rollback is not None:
        desc = f"{desc} - rolling back to job version {rollback.version}"
    update = DeploymentStatusUpdate(
        deployment_id=d.id, status=DEPLOYMENT_STATUS_FAILED,
        status_description=desc)
    ev = make_watcher_eval(d, job)
    payload = dict(update=update, evals=[ev])
    if rollback is not None:
        rolled = rollback.copy()
        rolled.stable = False
        rolled.version = 0          # reassigned by upsert_job
        payload["job"] = rolled
    server.raft_apply("deployment_status_update", payload)
    if rollback is not None:
        LOG.info("deployment %s failed; rolled %s back to version %d",
                 d.id[:8], d.job_id, rollback.version)
    return ev


def pause_deployment(server, deployment_id: str, pause: bool) -> None:
    """Deployment.Pause (deployment_watcher.go PauseDeployment:233)."""
    from ..models.deployment import DESC_RUNNING
    d = server.store.deployment_by_id(deployment_id)
    if d is None:
        raise KeyError(f"deployment {deployment_id} not found")
    if not d.active():
        raise ValueError(f"deployment {deployment_id} has terminal status "
                         f"{d.status}")
    if pause:
        update = DeploymentStatusUpdate(
            deployment_id=d.id, status=DEPLOYMENT_STATUS_PAUSED,
            status_description="Deployment is paused")
    else:
        update = DeploymentStatusUpdate(
            deployment_id=d.id, status=DEPLOYMENT_STATUS_RUNNING,
            status_description=DESC_RUNNING)
    server.raft_apply("deployment_status_update", dict(update=update, evals=[]))


def latest_stable_job(store, d: Deployment):
    """Newest job version flagged stable, excluding the deployed one
    (deployment_watcher.go latestStableJob:760)."""
    best = None
    for v in store.job_versions(d.namespace, d.job_id):
        if v.stable and v.version != d.job_version \
                and (best is None or v.version > best.version):
            best = v
    return best
