"""PeriodicDispatch: cron-style launcher for periodic jobs.

Reference semantics: nomad/periodic.go — the leader tracks every
periodic job (PeriodicDispatch.Add:208), keeps a heap of next launch
times, and at each fire derives a child job named
`<parent>/periodic-<unix>` (periodic.go deriveJob / structs.go
JobPeriodicLaunchSuffix), records the launch in the periodic_launch
table, and registers the child (creating a normal eval). prohibit_overlap
skips a launch while a previous child is non-terminal. ForceRun backs
`nomad job periodic force`.
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..models import Evaluation, Job, JOB_STATUS_DEAD, EVAL_STATUS_PENDING
from ..models.evaluation import TRIGGER_PERIODIC_JOB
from ..utils.cron import Cron, CronParseError
from ..utils.locks import make_condition, make_lock

LOG = logging.getLogger("nomad_tpu.periodic")

PERIODIC_LAUNCH_SUFFIX = "/periodic-"


class PeriodicDispatch:
    def __init__(self, server):
        self.srv = server
        self._lock = make_lock()
        self._tracked: Dict[Tuple[str, str], Job] = {}
        # heap entries carry a generation; re-adding a job bumps the
        # generation so stale entries are discarded on pop instead of
        # firing duplicate launches (periodic.go Add updates in place)
        self._gen: Dict[Tuple[str, str], int] = {}
        self._heap: List[Tuple[float, Tuple[str, str], int]] = []
        self._wake = make_condition(self._lock)
        self._enabled = False
        self._thread: Optional[threading.Thread] = None
        self._stopped = False

    # -- lifecycle (leader.go enables on leadership) -------------------
    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if not enabled:
                self._tracked.clear()
                self._heap.clear()
            self._wake.notify_all()
        if enabled and self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="periodic-dispatch")
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            self._enabled = False
            self._wake.notify_all()

    # -- tracking ------------------------------------------------------
    def add(self, job: Job) -> None:
        """Track (or retrack) a periodic job; untrack if it stopped being
        periodic (periodic.go Add:208)."""
        with self._lock:
            if not self._enabled:
                return
            key = (job.namespace, job.id)
            gen = self._gen.get(key, 0) + 1
            self._gen[key] = gen
            if not job.is_periodic() or job.stopped():
                self._tracked.pop(key, None)
                return
            self._tracked[key] = job
            nxt = self._next_launch(job, time.time())
            if nxt > 0:
                heapq.heappush(self._heap, (nxt, key, gen))
                self._wake.notify_all()

    def remove(self, namespace: str, job_id: str) -> None:
        with self._lock:
            key = (namespace, job_id)
            self._tracked.pop(key, None)
            self._gen[key] = self._gen.get(key, 0) + 1

    def tracked(self) -> List[Job]:
        with self._lock:
            return list(self._tracked.values())

    @staticmethod
    def _next_launch(job: Job, after: float) -> float:
        try:
            return Cron(job.periodic.spec).next_after(after)
        except CronParseError:
            LOG.warning("job %s has invalid cron %r", job.id,
                        job.periodic.spec)
            return 0.0

    # -- firing --------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._lock:
                if self._stopped:
                    return
                if not self._enabled or not self._heap:
                    self._wake.wait(0.2)
                    continue
                when, key, gen = self._heap[0]
                now = time.time()
                if when > now:
                    self._wake.wait(min(when - now, 0.5))
                    continue
                heapq.heappop(self._heap)
                if self._gen.get(key) != gen:
                    continue  # superseded by a newer add/remove
                job = self._tracked.get(key)
            if job is None:
                continue
            try:
                self.force_run(job.namespace, job.id, launch_time=when)
            except Exception:
                LOG.exception("periodic launch of %s failed", key)
            with self._lock:
                job = self._tracked.get(key)
                if job is not None and self._gen.get(key) == gen:
                    # compute from now, not the scheduled time: missed
                    # windows (suspend, stall) are skipped, not burst-
                    # replayed (periodic.go nextLaunch from time.Now())
                    nxt = self._next_launch(job, max(when, time.time()))
                    if nxt > 0:
                        heapq.heappush(self._heap, (nxt, key, gen))

    def force_run(self, namespace: str, job_id: str,
                  launch_time: float = 0.0) -> Optional[Evaluation]:
        """Launch one instance now (periodic.go ForceRun / createEval).
        Returns the eval for the child job, or None if skipped."""
        launch_time = launch_time or time.time()
        job = self.srv.store.job_by_id(namespace, job_id)
        if job is None or not job.is_periodic() or job.stopped():
            raise ValueError(f"job {job_id} is not a tracked periodic job")
        if job.periodic.prohibit_overlap and self._has_running_child(job):
            LOG.info("skipping launch of %s: prohibit_overlap and a child "
                     "is still running", job_id)
            return None
        # duplicate-launch guard: child IDs are stamped with whole
        # seconds, so a second launch in the same second would clobber
        # the first child (periodic.go createEval checks the
        # periodic_launch table the same way)
        last = self.srv.store.periodic_launch(namespace, job_id)
        if last is not None and int(last) >= int(launch_time):
            LOG.info("skipping launch of %s: already launched at %d",
                     job_id, int(last))
            return None
        child = self.derive_job(job, launch_time)
        ev = self.srv.register_job(child, triggered_by=TRIGGER_PERIODIC_JOB)
        self.srv.raft_apply("periodic_launch",
                            dict(namespace=namespace, job_id=job_id,
                                 launch_time=launch_time))
        return ev

    def _has_running_child(self, parent: Job) -> bool:
        for child in self.srv.store.jobs_by_parent(parent.namespace,
                                                   parent.id):
            if child.status != JOB_STATUS_DEAD:
                return True
        return False

    @staticmethod
    def derive_job(parent: Job, launch_time: float) -> Job:
        """periodic.go deriveJob: a copy with the launch-stamped ID, the
        parent link, and the periodic stanza stripped so the child is an
        ordinary one-shot job."""
        child = parent.copy()
        child.id = f"{parent.id}{PERIODIC_LAUNCH_SUFFIX}{int(launch_time)}"
        child.parent_id = parent.id
        child.periodic = None
        child.status = ""
        child.stable = False
        child.version = 0
        return child
