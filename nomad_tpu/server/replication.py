"""Cross-region replication from the authoritative region.

Reference: nomad/leader.go — replicateNamespaces:352,
replicateACLPolicies:1285, replicateACLTokens (only GLOBAL tokens
replicate; local tokens stay regional). A non-authoritative region's
leader long-polls the authoritative region's list endpoints, two-way
diffs against local state on modify_index, fetches changed full bodies,
and lands the result through its own raft. Transport here is the
federation HTTP surface (the same region-peer addresses the agents
use for request forwarding) instead of the reference's region-keyed
msgpack RPC.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request
from typing import Dict, Optional
from urllib.parse import urlencode

LOG = logging.getLogger("nomad_tpu.server.replication")

ERR_BACKOFF_S = 2.0
WAIT = "300s"


class ReplicationManager:
    """Leader-lifetime replication threads (one per replicated table).
    Started by establish_leadership on non-authoritative regions,
    stopped on revoke."""

    def __init__(self, server):
        self.server = server
        self.peer = server.config.region_peers.get(
            server.config.authoritative_region, "")
        self.token = server.config.replication_token
        self._stop = threading.Event()
        self._threads = []
        # name -> REMOTE modify_index at last sync. The local store
        # re-stamps modify_index with its own raft index on apply, so
        # diffing against local state alone would re-upsert everything
        # on every wakeup; this cache converges the diff. Per-term
        # (in-memory): a new leader re-syncs once, which is idempotent.
        self._synced: Dict[str, Dict[str, int]] = {
            "namespaces": {}, "policies": {}, "tokens": {}}

    def start(self) -> None:
        if not self.peer:
            LOG.warning("authoritative region %r has no region-peer "
                        "address; replication disabled",
                        self.server.config.authoritative_region)
            return
        for name, fn in (("namespaces", self._replicate_namespaces),
                         ("acl-policies", self._replicate_policies),
                         ("acl-tokens", self._replicate_tokens)):
            th = threading.Thread(target=self._loop, args=(name, fn),
                                  daemon=True, name=f"replicate-{name}")
            th.start()
            self._threads.append(th)

    def stop(self) -> None:
        self._stop.set()

    # -- transport -----------------------------------------------------
    def _get(self, path: str, params: Optional[dict] = None):
        url = f"http://{self.peer}{path}"
        if params:
            url += "?" + urlencode(params)
        req = urllib.request.Request(url)
        if self.token:
            req.add_header("X-Nomad-Token", self.token)
        with urllib.request.urlopen(req, timeout=330) as resp:
            ridx = resp.headers.get("X-Nomad-Index")
            return (json.loads(resp.read() or "null"),
                    int(ridx) if ridx else 0)

    def _loop(self, name: str, fn) -> None:
        """Long-poll the remote index; on change run one diff+apply
        round. Errors back off instead of spinning."""
        index = 0
        while not self._stop.is_set():
            try:
                index = fn(index)
            except Exception as e:
                LOG.warning("replication of %s from %r failed: %s",
                            name, self.peer, e)
                self._stop.wait(ERR_BACKOFF_S)

    # -- tables --------------------------------------------------------
    def _replicate_namespaces(self, index: int) -> int:
        from ..models.namespace import Namespace
        remote, ridx = self._get("/v1/namespaces",
                                 {"index": index, "wait": WAIT})
        from ..utils.codec import from_wire
        want = {w["name"]: from_wire(Namespace, w) for w in remote or []}
        synced = self._synced["namespaces"]
        local = {n.name: n for n in self.server.store.namespaces()}
        upserts = [ns for name, ns in want.items()
                   if name not in local
                   or synced.get(name) != ns.modify_index]
        doomed = [name for name in local
                  if name not in want and name != "default"]
        if upserts:
            self.server.raft_apply("namespace_upsert",
                                   dict(namespaces=upserts))
            for ns in upserts:
                synced[ns.name] = ns.modify_index
        if doomed:
            self.server.raft_apply("namespace_delete", dict(names=doomed))
            for name in doomed:
                synced.pop(name, None)
        return ridx if ridx else index

    def _replicate_policies(self, index: int) -> int:
        from ..acl import AclPolicy
        from ..utils.codec import from_wire
        remote, ridx = self._get("/v1/acl/policies",
                                 {"index": index, "wait": WAIT})
        want = {w["name"]: w["modify_index"] for w in remote or []}
        synced = self._synced["policies"]
        local = {p.name: p for p in self.server.store.acl_policies()}
        upserts = []
        for name, midx in want.items():
            if name in local and synced.get(name) == midx:
                continue
            body, _ = self._get(f"/v1/acl/policy/{name}")
            if body is not None:
                upserts.append((from_wire(AclPolicy, body), midx))
        doomed = [name for name in local if name not in want]
        if upserts:
            self.server.raft_apply(
                "acl_policy_upsert",
                dict(policies=[p for p, _m in upserts]))
            for p, midx in upserts:
                synced[p.name] = midx
        if doomed:
            self.server.raft_apply("acl_policy_delete",
                                   dict(names=doomed))
            for name in doomed:
                synced.pop(name, None)
        return ridx

    def _replicate_tokens(self, index: int) -> int:
        """Only GLOBAL tokens replicate (leader.go diffACLTokens —
        local tokens belong to their region)."""
        from ..acl import AclToken
        from ..utils.codec import from_wire
        remote, ridx = self._get("/v1/acl/tokens",
                                 {"index": index, "wait": WAIT})
        want: Dict[str, int] = {}
        for w in remote or []:
            if w.get("global") or w.get("global_"):
                want[w["accessor_id"]] = w["modify_index"]
        synced = self._synced["tokens"]
        local = {t.accessor_id: t
                 for t in self.server.store.acl_tokens() if t.global_}
        upserts = []
        for accessor, midx in want.items():
            if accessor in local and synced.get(accessor) == midx:
                continue
            body, _ = self._get(f"/v1/acl/token/{accessor}")
            if body is not None:
                upserts.append((from_wire(AclToken, body), midx))
        doomed = [a for a in local if a not in want]
        if upserts:
            self.server.raft_apply(
                "acl_token_upsert",
                dict(tokens=[t for t, _m in upserts]))
            for t, midx in upserts:
                synced[t.accessor_id] = midx
        if doomed:
            self.server.raft_apply("acl_token_delete",
                                   dict(accessor_ids=doomed))
            for a in doomed:
                synced.pop(a, None)
        return ridx
