"""Distributed scheduler plane (ISSUE 16): follower scheduling over
fenced local snapshots, leader-only verify/commit.

The Omega shape (SURVEY §2.2/§2.6) applied to the cluster the chaos
matrix already stands up: every server runs optimistic schedulers
against its OWN replicated MVCC store, and a single authority — the
raft leader — verifies and commits, which is exactly what the r9
group-commit plan applier terminates. Concretely:

  leader   the eval broker grows a remote-dequeue RPC surface
           (`Eval.Dequeue`/`Eval.Ack`/`Eval.Nack`), each remote
           dequeue covered by a LEASE (EvalLeaseTable) so a dead
           follower's evals are nacked back to READY instead of
           waiting out the broker's full 60 s unack timer;
           `Plan.Submit` feeds remote plans into the SAME plan queue
           local workers use, so the group-commit applier verifies
           local and remote members against one snapshot and demotes
           stale remote plans with the group's commit index as the
           refresh fence — exactly like local retries
  follower a FollowerScheduler runs full worker pools (Worker
           subclass — fence, gateway, tracing all inherited) whose
           broker is the leader reached over RPC and whose Planner
           lane submits plans back through `Plan.Submit`; scheduling
           reads come from the follower's LOCAL store, gated by the
           snapshot-min-index fence (`store.snapshot_min_index`
           blocks until local raft catch-up reaches the eval's
           modify_index; the wait surfaces as the `fence_wait` stage
           and a fence timeout NACKS the eval — never drops it)

Leadership transfer is seamless by construction: the new leader's
`establish_leadership` re-enqueues every non-terminal eval from the
store (Server._restore_evals), the old leader's lease table flushes on
revoke, in-flight remote leases expire back to READY, and follower
dequeue loops re-home via raft's leader hint with the SWIM member
list as the fallback directory (`_probe_for_leader`).

The whole plane degrades to r15 behavior with `follower_sched=false`
or NOMAD_TPU_FOLLOWER_SCHED=0 — no loops start, no verbs are called,
the leader schedules alone.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..models import Evaluation, Plan, PlanResult
from ..models.deployment import Deployment, DeploymentStatusUpdate
from ..chaos import faults as chaos_faults
from ..rpc.codec import RpcError
from ..utils.codec import from_wire, to_wire
from ..utils.locks import make_lock
from .worker import RAFT_SYNC_LIMIT, EvalLane, Worker

LOG = logging.getLogger("nomad_tpu.follower_sched")

# queues follower workers may drain remotely: placement schedulers
# only — `_core` housekeeping evals mutate through leader-local
# pseudo-schedulers and stay home
REMOTE_SCHEDULERS = ("service", "batch", "system")

# leader-side bound on one remote dequeue's long-poll: the handler
# thread parks in the broker at most this long, the follower simply
# calls again (its own DEQUEUE_TIMEOUT_S cadence over RPC)
DEQUEUE_POLL_BOUND_S = 2.0

LEASE_SWEEP_S = 0.25


def follower_sched_enabled() -> bool:
    """Env kill switch (the NOMAD_TPU_PLAN_GROUP idiom): =0 means no
    follower loops start anywhere, whatever the ServerConfig says."""
    return os.environ.get("NOMAD_TPU_FOLLOWER_SCHED", "1") != "0"


# -- wire helpers ------------------------------------------------------
# Plan/PlanResult are wire-able dataclasses except their deployment
# fields, typed Optional[object] (persistence.SCHEMAS owns the typed
# decode for raft entries) — re-type them here the same way.

def decode_plan(data: dict) -> Plan:
    plan = from_wire(Plan, data)
    if isinstance(plan.deployment, dict):
        plan.deployment = from_wire(Deployment, plan.deployment)
    plan.deployment_updates = [
        from_wire(DeploymentStatusUpdate, u) if isinstance(u, dict) else u
        for u in (plan.deployment_updates or [])]
    return plan


def decode_plan_result(data: dict) -> PlanResult:
    result = from_wire(PlanResult, data)
    if isinstance(result.deployment, dict):
        result.deployment = from_wire(Deployment, result.deployment)
    result.deployment_updates = [
        from_wire(DeploymentStatusUpdate, u) if isinstance(u, dict) else u
        for u in (result.deployment_updates or [])]
    return result


# -- leader side: the lease table --------------------------------------

class _Lease:
    __slots__ = ("token", "follower", "deadline")

    def __init__(self, token: str, follower: str, deadline: float):
        self.token = token
        self.follower = follower
        self.deadline = deadline


class EvalLeaseTable:
    """Leader-side ledger of evals dequeued by remote followers.

    The broker's own 60 s unack timer is the backstop; the lease is the
    FAST path — a follower that dies (or partitions away) mid-eval gets
    its evals nacked back to READY after `follower_lease_s` with ZERO
    re-enqueue delay (the follower failed, not the eval). One sweeper
    thread (started lazily on the first grant, stopped at shutdown)
    scans deadlines; per-lease timers would leak OS timer threads at
    C2M dequeue rates and tangle shutdown ordering.

    Also the home of the leader-side scheduler-plane counters the
    governor's `cluster_sched.*` gauges read — it exists from
    Server.__init__ on every server (gauge registration precedes
    attach_raft), and is simply empty on non-leaders.
    """

    def __init__(self, server):
        self.server = server
        self._l = make_lock()
        self._leases: Dict[str, _Lease] = {}      # eval id -> lease
        self._sweeper: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.stats = {"granted": 0, "released": 0, "expired": 0,
                      "remote_dequeues": 0, "remote_plans": 0,
                      "remote_demotions": 0}

    # -- grants --------------------------------------------------------
    def grant(self, eval_id: str, token: str, follower: str,
              lease_s: float) -> None:
        with self._l:
            self._leases[eval_id] = _Lease(
                token, follower, time.monotonic() + max(lease_s, 0.5))
            self.stats["granted"] += 1
            self.stats["remote_dequeues"] += 1
            self._ensure_sweeper()

    def release(self, eval_id: str, token: str) -> bool:
        with self._l:
            lease = self._leases.get(eval_id)
            if lease is not None and lease.token == token:
                del self._leases[eval_id]
                self.stats["released"] += 1
                return True
            return False

    def note_plan(self, result: PlanResult) -> None:
        with self._l:
            self.stats["remote_plans"] += 1
            if result.refresh_index:
                self.stats["remote_demotions"] += 1

    # -- introspection (gauges, CLI columns, operator debug) -----------
    def outstanding(self) -> int:
        with self._l:
            return len(self._leases)

    def by_follower(self) -> Dict[str, int]:
        with self._l:
            out: Dict[str, int] = {}
            for lease in self._leases.values():
                out[lease.follower] = out.get(lease.follower, 0) + 1
            return out

    def snapshot_stats(self) -> dict:
        with self._l:
            return {**self.stats, "outstanding": len(self._leases)}

    # -- lifecycle -----------------------------------------------------
    def flush(self) -> None:
        """Leadership revoked: the broker flush already cancelled every
        unack, so the leases are moot — just forget them."""
        with self._l:
            self._leases.clear()

    def stop(self) -> None:
        self._stop.set()
        sweeper = self._sweeper
        if sweeper is not None:
            sweeper.join(timeout=2.0)

    def _ensure_sweeper(self) -> None:
        # self._l held
        if self._sweeper is None and not self._stop.is_set():
            self._sweeper = threading.Thread(
                target=self._sweep_loop, daemon=True, name="eval-leases")
            self._sweeper.start()

    def _sweep_loop(self) -> None:
        while not self._stop.wait(LEASE_SWEEP_S):
            now = time.monotonic()
            with self._l:
                expired = [(eid, lease) for eid, lease
                           in self._leases.items()
                           if lease.deadline <= now]
                for eid, _lease in expired:
                    del self._leases[eid]
                self.stats["expired"] += len(expired)
            for eid, lease in expired:
                LOG.debug("lease expired for eval %s (follower %s); "
                          "nacking back to READY", eid[:8], lease.follower)
                try:
                    # immediate re-enqueue: the FOLLOWER died, the eval
                    # did nothing wrong — no nack backoff
                    self.server.eval_broker.nack(eid, lease.token,
                                                 delay_s=0.0)
                except Exception:   # pragma: no cover — broker flushed
                    pass


# -- leader side: the RPC verbs ----------------------------------------

def rpc_handlers(server) -> Dict:
    """The scheduler-plane verb table, merged into the RPC method table
    by Server.attach_raft. Handlers never raise for expected cluster
    conditions (not-leader, unknown token): a raised handler error is
    logged server-side and surfaces as an opaque RpcError — structured
    replies keep follower re-homing quiet and teardown clean."""

    def _is_leader() -> bool:
        raft = server.raft
        return raft is None or raft.is_leader()

    def _not_leader() -> dict:
        raft = server.raft
        return {"not_leader": True,
                "leader": getattr(raft, "leader_addr", None)}

    def eval_dequeue(args: dict) -> dict:
        if not _is_leader():
            return _not_leader()
        broker = server.eval_broker
        if not broker.enabled():
            return {"eval": None}
        timeout = min(float(args.get("timeout_s") or 0.5),
                      DEQUEUE_POLL_BOUND_S)
        scheds = [s for s in (args.get("schedulers") or [])
                  if s in REMOTE_SCHEDULERS]
        if not scheds:
            return {"eval": None}
        ev, token = broker.dequeue(scheds, timeout_s=timeout)
        if ev is None:
            return {"eval": None}
        server.eval_leases.grant(
            ev.id, token, follower=str(args.get("follower") or ""),
            lease_s=float(server.config.follower_lease_s))
        return {"eval": to_wire(ev), "token": token,
                "queue_wait_s": float(getattr(ev, "queue_wait_s", 0.0))}

    def eval_ack(args: dict) -> dict:
        eval_id, token = args["eval_id"], args["token"]
        server.eval_leases.release(eval_id, token)
        try:
            server.eval_broker.ack(eval_id, token)
        except (KeyError, ValueError) as e:
            # lease already expired (eval redelivered) or broker
            # flushed across a failover — the follower's work stands
            # or was redone; nothing to crash about
            return {"ok": False, "error": str(e)}
        return {"ok": True}

    def eval_nack(args: dict) -> dict:
        eval_id, token = args["eval_id"], args["token"]
        server.eval_leases.release(eval_id, token)
        server.eval_broker.nack(eval_id, token)     # token-checked no-op
        return {"ok": True}                         # when already gone

    def eval_reblock(args: dict) -> dict:
        if not _is_leader():
            return _not_leader()
        ev = from_wire(Evaluation, args["eval"])
        server.blocked_evals.block(ev)
        return {"ok": True}

    def plan_submit(args: dict) -> dict:
        if not _is_leader():
            return _not_leader()
        try:
            plan = decode_plan(args["plan"])
            future = server.plan_queue.enqueue(plan, remote=True)
            result: PlanResult = future.result(timeout=30.0)
        except Exception as e:
            # stale token / queue disabled / leadership lost mid-commit:
            # the follower nacks and the eval redelivers — a structured
            # error, not a traceback
            return {"error": f"{type(e).__name__}: {e}"}
        server.eval_leases.note_plan(result)
        return {"result": to_wire(result)}

    return {
        "Eval.Dequeue": eval_dequeue,
        "Eval.Ack": eval_ack,
        "Eval.Nack": eval_nack,
        "Eval.Reblock": eval_reblock,
        "Plan.Submit": plan_submit,
    }


# -- follower side -----------------------------------------------------

class RemoteBroker:
    """The follower worker's eval source/sink: the leader's broker
    reached over RPC. Duck-typed to the three calls Worker makes
    (dequeue/ack/nack); every path swallows transport errors — a lost
    ack costs one lease expiry, never a crashed worker loop."""

    def __init__(self, fs: "FollowerScheduler"):
        self.fs = fs

    def dequeue(self, schedulers: List[str],
                timeout_s: Optional[float] = None
                ) -> Tuple[Optional[Evaluation], str]:
        fs = self.fs
        pause = min(max(timeout_s or 0.05, 0.05), 0.5)
        if not fs.active():
            # leader locally (its own workers drain the broker
            # directly), disabled, or stopping: idle at the dequeue
            # cadence so a role flip picks the loop right back up
            fs.wait(pause)
            return None, ""
        addr = fs.leader_addr()
        if not addr:
            fs.wait(pause)
            return None, ""
        try:
            res = fs.call(addr, "Eval.Dequeue",
                          {"schedulers": list(schedulers),
                           "timeout_s": timeout_s or 0.5,
                           "follower": fs.self_addr()},
                          timeout_s=(timeout_s or 0.5)
                          + DEQUEUE_POLL_BOUND_S + 3.0)
        except Exception:
            fs.note_leader_lost(addr)
            fs.wait(pause)
            return None, ""
        if res.get("not_leader"):
            fs.rehome(res.get("leader"))
            return None, ""
        data = res.get("eval")
        if not data:
            return None, ""
        ev = from_wire(Evaluation, data)
        # queue-wait attribution rides the response (dynamic attrs
        # don't survive to_wire): the follower's stage report and
        # governor reservoir see the leader-side READY wait
        ev.queue_wait_s = float(res.get("queue_wait_s") or 0.0)
        fs.incr("remote_dequeues")
        return ev, str(res.get("token") or "")

    def ack(self, eval_id: str, token: str) -> None:
        if not self._finish("Eval.Ack", eval_id, token):
            self.fs.incr("ack_failures")

    def nack(self, eval_id: str, token: str) -> None:
        if not self._finish("Eval.Nack", eval_id, token):
            self.fs.incr("nack_failures")

    def _finish(self, verb: str, eval_id: str, token: str) -> bool:
        fs = self.fs
        addr = fs.leader_addr()
        if not addr:
            return False
        try:
            res = fs.call(addr, verb,
                          {"eval_id": eval_id, "token": token},
                          timeout_s=5.0)
        except Exception:
            # leader gone: the lease expires (or the new leader's
            # broker was rebuilt from the store) — redelivery is the
            # protocol, not an error
            fs.note_leader_lost(addr)
            return False
        return bool(res.get("ok"))


class RemoteEvalLane(EvalLane):
    """Planner lane for one remotely-dequeued eval: plans flow to the
    leader's plan queue over `Plan.Submit`; refresh fences are honored
    against the LOCAL store (replication delivers the group's commit
    by the time block_min_index returns, same as a local retry)."""

    def __init__(self, fs: "FollowerScheduler", server, ev: Evaluation,
                 token: str):
        super().__init__(server, ev, token)
        self.fs = fs

    def submit_plan(self, plan: Plan) -> Optional[PlanResult]:
        from ..utils import metrics
        t0 = time.monotonic()
        plan.eval_id = self.eval.id
        plan.eval_token = self.token
        plan.snapshot_index = self.snapshot_index
        fs = self.fs
        addr = fs.leader_addr()
        if not addr:
            raise RpcError("no cluster leader for Plan.Submit")
        res = fs.call(addr, "Plan.Submit",
                      {"plan": to_wire(plan), "follower": fs.self_addr()},
                      timeout_s=35.0)
        if res.get("not_leader"):
            fs.rehome(res.get("leader"))
            raise RpcError("Plan.Submit: leadership moved")
        if res.get("error"):
            raise RpcError(f"Plan.Submit failed: {res['error']}")
        result = decode_plan_result(res.get("result") or {})
        fs.incr("remote_plans")
        if chaos_faults.ACTIVE:
            # same hook, same point in the protocol as the local lane:
            # the plan IS committed (leader-side) and the eval is not
            # yet acked — a worker-kill fault here exercises redelivery
            # across the remote path too
            chaos_faults.fire(
                "worker.plan_committed", eval_id=self.eval.id,
                placements=sum(len(a) for a in
                               plan.node_allocation.values()))
        metrics.measure_since("nomad.worker.submit_plan", t0)
        if result.refresh_index:
            # demoted (entirely or partially): the group's commit index
            # is the refresh fence — wait for LOCAL replication to
            # catch up so the retry sees why it lost
            fs.incr("demoted_plans")
            self.server.store.block_min_index(result.refresh_index - 1,
                                              timeout_s=RAFT_SYNC_LIMIT)
        return result

    def reblock_eval(self, ev: Evaluation) -> None:
        fs = self.fs
        addr = fs.leader_addr()
        if not addr:
            raise RpcError("no cluster leader for Eval.Reblock")
        res = fs.call(addr, "Eval.Reblock", {"eval": to_wire(ev)},
                      timeout_s=5.0)
        if res.get("not_leader"):
            fs.rehome(res.get("leader"))
            raise RpcError("Eval.Reblock: leadership moved")


class FollowerWorker(Worker):
    """A full scheduling worker whose broker is remote and whose lane
    submits plans back to the leader. Everything else — the snapshot
    fence, the micro-batch gateway, tracing, the finisher pipeline —
    is inherited; the fence timeout shrinks to the configured
    `follower_fence_timeout_s` and a timeout NACKS (worker.py)."""

    def __init__(self, fs: "FollowerScheduler", wid: int):
        super().__init__(fs.server, list(REMOTE_SCHEDULERS), wid=wid)
        self.fs = fs
        self.broker = RemoteBroker(fs)
        # remote lanes already overlap across workers; per-worker
        # drain batching would add a dequeue RPC per drained eval
        self.batch_size = 1
        self.fence_timeout_s = float(fs.fence_timeout_s)

    def _make_lane(self, ev: Evaluation, token: str) -> EvalLane:
        return RemoteEvalLane(self.fs, self.server, ev, token)

    def _note_fence(self, seconds: float) -> None:
        super()._note_fence(seconds)
        self.fs.note_fence_wait(seconds)


class FollowerScheduler:
    """Per-server owner of the remote scheduling loops: follower
    workers, the cached leader RPC clients, and the re-homing
    directory. Built in Server.attach_raft (needs the raft identity),
    started by Server.start, stopped FIRST in Server.shutdown so no
    loop is mid-RPC while local transports die."""

    def __init__(self, server):
        cfg = server.config
        self.server = server
        self.configured = bool(getattr(cfg, "follower_sched", True))
        self.lease_s = float(getattr(cfg, "follower_lease_s", 30.0))
        self.fence_timeout_s = float(
            getattr(cfg, "follower_fence_timeout_s", 5.0))
        self.max_remote = int(getattr(cfg, "follower_max_remote", 2))
        self._l = make_lock()
        self._clients: Dict[str, object] = {}
        self._leader_hint: Optional[str] = None
        self._stop = threading.Event()
        self.workers: List[FollowerWorker] = []
        self.stats = {"remote_dequeues": 0, "remote_plans": 0,
                      "demoted_plans": 0, "ack_failures": 0,
                      "nack_failures": 0, "rehomes": 0}
        # fence-wait reservoir for the cluster_sched.fence_wait_p99_ms
        # gauge and the bench artifact (bounded; p99 over recent waits)
        self._fence_res: deque = deque(maxlen=512)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if (not self.configured or not follower_sched_enabled()
                or self.server.raft is None or self.max_remote <= 0):
            return
        base = int(getattr(self.server.config, "num_schedulers", 0))
        for i in range(self.max_remote):
            w = FollowerWorker(self, wid=base + i)
            w.start()
            self.workers.append(w)
        LOG.info("follower scheduler: %d remote workers started",
                 len(self.workers))

    def stop(self) -> None:
        self._stop.set()
        for w in self.workers:
            w.stop()
        self.workers = []
        with self._l:
            clients, self._clients = dict(self._clients), {}
        for c in clients.values():
            try:
                c.close()
            except Exception:
                pass

    def set_pause(self, paused: bool) -> None:
        for w in self.workers:
            w.set_pause(paused)

    def wait(self, seconds: float) -> None:
        self._stop.wait(seconds)

    def active(self) -> bool:
        if self._stop.is_set():
            return False
        raft = self.server.raft
        return (raft is not None and not raft.is_leader()
                and not getattr(raft, "removed", False))

    # -- stats ---------------------------------------------------------
    def incr(self, key: str, n: int = 1) -> None:
        with self._l:
            self.stats[key] = self.stats.get(key, 0) + n

    def note_fence_wait(self, seconds: float) -> None:
        with self._l:
            self._fence_res.append(seconds)

    def fence_wait_p99_ms(self) -> float:
        with self._l:
            if not self._fence_res:
                return 0.0
            waits = sorted(self._fence_res)
        return waits[min(len(waits) - 1,
                         int(0.99 * len(waits)))] * 1e3

    def snapshot_stats(self) -> dict:
        with self._l:
            out = dict(self.stats)
        out["fence_wait_p99_ms"] = round(self.fence_wait_p99_ms(), 3)
        out["workers"] = len(self.workers)
        return out

    # -- leader directory ----------------------------------------------
    def self_addr(self) -> str:
        raft = self.server.raft
        return raft.self_addr if raft is not None else ""

    def leader_addr(self) -> Optional[str]:
        raft = self.server.raft
        if raft is None:
            return None
        addr = raft.leader_addr
        if addr and addr != raft.self_addr:
            return addr
        with self._l:
            hint = self._leader_hint
        if hint and hint != raft.self_addr:
            return hint
        return self._probe_for_leader()

    def rehome(self, leader: Optional[str]) -> None:
        """A peer told us who leads now (or that our target doesn't):
        adopt the hint and drop the stale client."""
        with self._l:
            if leader and leader != self._leader_hint:
                self._leader_hint = leader
                self.stats["rehomes"] += 1
            elif not leader:
                self._leader_hint = None

    def note_leader_lost(self, addr: str) -> None:
        with self._l:
            if self._leader_hint == addr:
                self._leader_hint = None
            client = self._clients.pop(addr, None)
        if client is not None:
            try:
                client.close()
            except Exception:
                pass

    def _probe_for_leader(self) -> Optional[str]:
        """Re-home through the SWIM member list: ask live members who
        leads (Raft.Status). SWIM's verdict filters the candidates —
        probing a FAILED member would just eat a dial timeout."""
        raft = self.server.raft
        if raft is None:
            return None
        swim = getattr(self.server, "swim", None)
        if swim is not None:
            members = swim.live_members()
        else:
            members = self.server.store.server_members() or []
        members = [m for m in members if m != raft.self_addr]
        random.shuffle(members)
        for addr in members:
            if self._stop.is_set():
                return None
            try:
                res = self.call(addr, "Raft.Status", {}, timeout_s=1.0)
            except Exception:
                continue
            if res.get("role") == "leader":
                self.rehome(addr)
                return addr
            hinted = res.get("leader")
            if hinted and hinted != raft.self_addr:
                self.rehome(hinted)
                return hinted
        return None

    # -- transport -----------------------------------------------------
    def call(self, addr: str, method: str, args: dict,
             timeout_s: float = 5.0):
        from ..rpc.client import RpcClient
        with self._l:
            client = self._clients.get(addr)
            if client is None:
                client = RpcClient(addr, dial_timeout_s=1.0)
                self._clients[addr] = client
        return client.call(method, args, timeout_s=timeout_s)
