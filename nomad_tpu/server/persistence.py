"""Server persistence: write-ahead log + state snapshots.

Reference semantics: the Raft log (raft-boltdb) + FSM snapshots
(nomad/fsm.go Snapshot:1360 persists every table, Restore:1374 rebuilds
memdb; nomad/server.go:1214 setupRaft). Single-node round 1: the log is
an append-only file of msgpack-framed (index, type, payload) entries
written BEFORE the FSM applies them (WAL discipline); snapshots dump the
whole store and truncate the log. Restore = load snapshot + replay the
log tail. The encode/decode schema per apply type lives here so a
replicated log can reuse it unchanged.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Any, BinaryIO, Dict, List, Optional, Tuple

import msgpack

from ..models import (Allocation, Deployment, Evaluation, Job, Node,
                      SchedulerConfiguration)
from ..models.alloc import DesiredTransition
from ..models.deployment import DeploymentStatusUpdate
from ..models.node import DrainStrategy
from ..utils.codec import from_wire, to_wire
from ..utils.locks import make_lock

# payload field -> model type (list-wrapped == repeated)
SCHEMAS: Dict[str, Dict[str, Any]] = {
    "job_register": {"job": Job, "evals": [Evaluation]},
    "job_deregister": {"evals": [Evaluation]},
    "eval_update": {"evals": [Evaluation]},
    "eval_delete": {},
    "node_register": {"node": Node},
    "node_deregister": {},
    "node_status_update": {"evals": [Evaluation]},
    "node_eligibility_update": {},
    "node_drain_update": {"drain_strategy": DrainStrategy},
    "alloc_client_update": {"allocs": [Allocation], "evals": [Evaluation]},
    "plan_results": {"allocs_stopped": [Allocation],
                     "allocs_placed": [Allocation],
                     "allocs_preempted": [Allocation],
                     "deployment": Deployment,
                     "deployment_updates": [DeploymentStatusUpdate],
                     "evals": [Evaluation]},
    # group-commit applier: one entry carrying N plan_results payloads
    # (encode/decode recurse per group member — see below)
    "plan_group_results": {},
    # batched write ingest (ISSUE 19): one entry carrying N kind-tagged
    # sub-payloads (job_register / alloc_client_update /
    # alloc_desired_transition); encode/decode recurse per entry by its
    # "kind" key — see below
    "ingest_batch": {},
    "scheduler_config": {"config": SchedulerConfiguration},
    "deployment_status_update": {"update": DeploymentStatusUpdate,
                                 "job": Job, "evals": [Evaluation]},
    "deployment_promotion": {"evals": [Evaluation]},
    "alloc_desired_transition": {"transition": DesiredTransition,
                                 "evals": [Evaluation]},
    "job_stability": {},
    "scaling_event": {},
    "server_membership": {},
    "noop": {},
    "deployment_delete": {},
    "periodic_launch": {},
}


def _register_acl_schemas() -> None:
    # deferred: nomad_tpu.acl imports jobspec which imports models —
    # registering lazily avoids a cycle at module import time
    from ..acl import AclPolicy, AclToken
    from ..models.csi import CSIVolume
    SCHEMAS.update({
        "acl_policy_upsert": {"policies": [AclPolicy]},
        "acl_policy_delete": {},
        "acl_token_upsert": {"tokens": [AclToken]},
        "acl_token_delete": {},
        "csi_volume_register": {"volumes": [CSIVolume]},
        "csi_volume_deregister": {},
        "csi_volume_claim": {},
        "csi_volume_release": {},
    })
    from .event_sink import EventSink
    SCHEMAS.update({
        "event_sink_upsert": {"sink": EventSink},
        "event_sink_delete": {},
        "event_sink_progress": {},
    })
    from ..models.services import ServiceRegistration
    SCHEMAS.update({
        "service_registration_upsert": {"services": [ServiceRegistration]},
        "service_registration_delete": {},
    })
    from ..models.namespace import Namespace
    SCHEMAS.update({
        "namespace_upsert": {"namespaces": [Namespace]},
        "namespace_delete": {},
    })


_register_acl_schemas()


def encode_payload(msg_type: str, payload: dict) -> dict:
    if msg_type == "plan_group_results":
        return {"groups": [encode_payload("plan_results", g)
                           for g in payload.get("groups", [])]}
    if msg_type == "ingest_batch":
        # each sub-entry encodes under its own kind's schema; the
        # "kind" tag itself is a plain string and rides through
        return {"entries": [encode_payload(e.get("kind", ""), e)
                            for e in payload.get("entries", [])]}
    out = {}
    for k, v in payload.items():
        out[k] = to_wire(v)
    return out


def decode_payload(msg_type: str, data: dict) -> dict:
    if msg_type == "plan_group_results":
        return {"groups": [decode_payload("plan_results", g)
                           for g in data.get("groups", [])]}
    if msg_type == "ingest_batch":
        return {"entries": [decode_payload(e.get("kind", ""), e)
                            for e in data.get("entries", [])]}
    schema = SCHEMAS.get(msg_type, {})
    out: dict = {}
    for k, v in data.items():
        hint = schema.get(k)
        if hint is None:
            out[k] = v
        elif isinstance(hint, list):
            out[k] = [from_wire(hint[0], x) for x in (v or [])]
        else:
            out[k] = from_wire(hint, v) if v is not None else None
    return out


class RaftLog:
    """Append-only WAL of msgpack frames: [u32 length][payload]."""

    def __init__(self, path: str):
        self.path = path
        self._l = make_lock()
        self._f: Optional[BinaryIO] = None
        self._good_offset: Optional[int] = None
        self._dirty = False      # flushed-but-not-fsynced bytes pending
        self._trunc_shift = 0    # bytes dropped by truncate_prefix

    def open(self) -> None:
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        # a torn tail from a crash must be truncated before appending,
        # or the garbage bytes poison every later frame on next replay
        if self._good_offset is not None and os.path.exists(self.path) \
                and os.path.getsize(self.path) > self._good_offset:
            with open(self.path, "r+b") as f:
                f.truncate(self._good_offset)
        self._f = open(self.path, "ab")

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None

    def append(self, index: int, msg_type: str, payload: dict,
               sync: bool = False) -> None:
        import time as _time
        frame = msgpack.packb(
            {"i": index, "t": msg_type, "ts": _time.time(),
             "p": encode_payload(msg_type, payload)},
            use_bin_type=True)
        with self._l:
            self._f.write(struct.pack("<I", len(frame)))
            self._f.write(frame)
            self._f.flush()
            if sync:
                os.fsync(self._f.fileno())
                self._dirty = False
            else:
                self._dirty = True

    def sync(self) -> None:
        """Group-fsync point: ONE fsync covers every append since the
        last sync (the WAL analog of the r9 group-commit applier — the
        raft FSM calls it once per committed apply batch)."""
        with self._l:
            if self._f is not None and self._dirty:
                self._f.flush()
                os.fsync(self._f.fileno())
                self._dirty = False

    def size(self) -> int:
        """Current ABSOLUTE stream position (bytes ever appended,
        including prefixes already truncated away) — the snapshot's
        truncation mark. Absolute marks stay valid even if another
        snapshot writer truncates the file between capture and use;
        `_trunc_shift` tracks the bytes removed so far."""
        with self._l:
            if self._f is not None:
                return self._trunc_shift + self._f.tell()
            phys = os.path.getsize(self.path) \
                if os.path.exists(self.path) else 0
            return self._trunc_shift + phys

    def truncate_prefix(self, mark: int) -> None:
        """Drop the log prefix before absolute position `mark` (covered
        by a completed snapshot), KEEPING the tail appended while the
        snapshot was serializing off-thread — a whole-file truncate
        here would lose entries the snapshot does not contain. A mark
        at or below an already-truncated prefix is a no-op, so two
        racing snapshot writers can never cut at a stale offset."""
        with self._l:
            local = mark - self._trunc_shift
            if local <= 0 or not os.path.exists(self.path):
                return
            was_open = self._f is not None
            if was_open:
                self._f.close()
                self._f = None
            with open(self.path, "rb") as f:
                f.seek(local)
                tail = f.read()
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(tail)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self._trunc_shift += local
            if was_open:
                self._f = open(self.path, "ab")
            self._dirty = False

    def replay(self) -> List[Tuple[int, str, dict]]:
        """Read all entries; tolerates a torn final frame (crash)."""
        out: List[Tuple[int, str, dict]] = []
        self._good_offset = 0
        if not os.path.exists(self.path):
            return out
        with open(self.path, "rb") as f:
            while True:
                header = f.read(4)
                if len(header) < 4:
                    break
                (length,) = struct.unpack("<I", header)
                frame = f.read(length)
                if len(frame) < length:
                    break  # torn write at crash: drop the tail
                try:
                    entry = msgpack.unpackb(frame, raw=False)
                    decoded = decode_payload(entry["t"], entry["p"])
                except Exception:
                    break  # corrupt frame: treat like a torn tail
                out.append((entry["i"], entry["t"], decoded,
                            entry.get("ts", 0.0)))
                self._good_offset = f.tell()
        return out

class Persistence:
    """Snapshot + WAL pair under a data directory."""

    SNAPSHOT = "state.snap"
    WAL = "raft.log"
    # measured per-(arm, n_pad) dispatch costs (ops/select.py
    # DispatchCostModel.snapshot() format: {"<arm>@<n_pad>":
    # {"ewma_s": float, "samples": int}}), persisted as JSON next to
    # the state snapshot so a restarted server's routing/batching
    # decisions start measured instead of cold (ISSUE 7). Host+device
    # local by construction — never replicated, safe to delete
    COST_MODEL = "cost_model.json"

    def __init__(self, data_dir: str, snapshot_every: int = 1024, *,
                 columnar: bool = True, background: bool = True,
                 wal_fsync: bool = False, wal_group_fsync: bool = True):
        self.data_dir = data_dir
        self.snapshot_every = snapshot_every
        # snapshot format 2 (state/columnar.py struct-of-arrays) vs the
        # legacy per-object dump; restore auto-detects either
        self.columnar = columnar
        # serialize + write snapshots on a background thread off an
        # O(1) MVCC store snapshot, so maybe_snapshot never stalls the
        # commit path
        self.background = background
        # WAL durability: fsync appends at all (off matches the
        # pre-r12 flush-only behavior), and whether a committed apply
        # batch pays ONE fsync (group) or one per entry
        self.wal_fsync = wal_fsync
        self.wal_group_fsync = wal_group_fsync
        os.makedirs(data_dir, exist_ok=True)
        self.log = RaftLog(os.path.join(data_dir, self.WAL))
        self._since_snapshot = 0
        self._l = make_lock()
        self._snap_l = make_lock()      # one snapshot writer
        self._trigger_l = make_lock()
        self._snap_thread: Optional[threading.Thread] = None
        # absolute WAL mark of the newest PUBLISHED snapshot: a writer
        # whose capture is older must not replace it (a sync snapshot
        # racing a slow background writer), monotone under _snap_l
        self._published_mark = -1
        # counters are += read-modify-writes from the applier (trigger
        # path, under _trigger_l), the writer thread (under _snap_l),
        # and boot restore — no shared lock between them, so they get
        # their own
        self._stats_l = make_lock()
        # nomad-lint: guarded-by[_stats_l]
        self.stats: Dict[str, Any] = {
            "snapshots": 0, "background_snapshots": 0,
            "snapshot_skipped_inflight": 0, "last_snapshot_s": 0.0,
            "last_snapshot_format": 0, "snapshot_errors": 0,
            "restore_s": 0.0, "restore_format": 0,
        }
        # server-level state (e.g. the GC TimeTable) rides along in the
        # snapshot under "extra"; the provider is set by the Server
        self.extra_provider = None
        # set by the Server: returns the live cost-model snapshot dict;
        # written on every state snapshot and at shutdown
        self.cost_model_provider = None
        self.restored_extra: dict = {}

    @property
    def snapshot_path(self) -> str:
        return os.path.join(self.data_dir, self.SNAPSHOT)

    @property
    def cost_model_path(self) -> str:
        return os.path.join(self.data_dir, self.COST_MODEL)

    def load_cost_model(self) -> dict:
        import json
        try:
            with open(self.cost_model_path) as f:
                data = json.load(f)
            return data if isinstance(data, dict) else {}
        except (OSError, ValueError):
            return {}

    def save_cost_model(self) -> None:
        import json
        if self.cost_model_provider is None:
            return
        snap = self.cost_model_provider()
        if not snap:
            return
        tmp = self.cost_model_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f, indent=0, sort_keys=True)
        os.replace(tmp, self.cost_model_path)

    def restore_into(self, store
                     ) -> Tuple[int, List[Tuple[int, str, dict, float]]]:
        """Load the snapshot into the store and read the WAL tail.
        Returns ``(highest, entries)``: the snapshot's highest applied
        index (0 if fresh) and the decoded WAL entries for the caller
        to replay (each ``(index, msg_type, payload, ts)``). Both
        snapshot formats restore here — the columnar format-2 file and
        the legacy per-object dump (state/store.py restore
        auto-detects). A leftover ``state.snap.tmp`` from a crash
        mid-snapshot is ignored (os.replace is atomic, so the prior
        snapshot + un-truncated WAL are intact) and cleaned up."""
        import time as _time
        from ..utils import stages
        t0 = _time.perf_counter()
        highest = 0
        tmp = self.snapshot_path + ".tmp"
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:     # pragma: no cover — best effort
                pass
        if os.path.exists(self.snapshot_path):
            with open(self.snapshot_path, "rb") as f:
                data = msgpack.unpackb(f.read(), raw=False,
                                       strict_map_key=False)
            # snapshot index tuples were listified by msgpack
            self.restored_extra = data.pop("extra", {}) or {}
            with self._stats_l:
                self.stats["restore_format"] = int(data.get("format", 1))
            store.restore(data)
            highest = store.latest_index()
        entries = self.log.replay()
        self.log.open()
        with self._stats_l:
            self.stats["restore_s"] = _time.perf_counter() - t0
        if stages.enabled:
            stages.add("restore", self.stats["restore_s"])
        return highest, entries

    def record(self, index: int, msg_type: str, payload: dict) -> None:
        self.log.append(index, msg_type, payload,
                        sync=self.wal_fsync and not self.wal_group_fsync)

    def commit_barrier(self) -> None:
        """Group-fsync boundary: called once per committed apply batch
        (raft.py _fsm_loop; the dev-mode apply calls it per entry —
        there the entry IS the commit unit). One fsync covers every
        record() since the last barrier."""
        if self.wal_fsync and self.wal_group_fsync:
            self.log.sync()

    def maybe_snapshot(self, store) -> None:
        """Called AFTER the FSM applied the entry — a snapshot capture
        here includes it, so dropping the covered WAL prefix is safe.
        Only TRIGGERS the snapshot: the capture is an O(1) MVCC root +
        WAL mark, and serialization/writing run on a background thread
        (snapshot_background), so the applier never blocks on a dump
        of a large store."""
        with self._l:
            self._since_snapshot += 1
            if self._since_snapshot < self.snapshot_every:
                return
            self._since_snapshot = 0
        self.trigger_snapshot(store)

    def trigger_snapshot(self, store) -> Optional[threading.Thread]:
        """Capture (MVCC snapshot, extra, WAL mark) NOW; serialize and
        write off-thread. Returns the writer thread, or None when the
        write ran inline (background off) or was skipped because one
        is already in flight (the next threshold retriggers)."""
        with self._trigger_l:
            t = self._snap_thread
            if t is not None and t.is_alive():
                with self._stats_l:
                    self.stats["snapshot_skipped_inflight"] += 1
                return None
            snap = store.snapshot()
            extra = self.extra_provider() \
                if self.extra_provider is not None else None
            mark = self.log.size()
            if not self.background:
                self._write_snapshot(snap, extra, mark)
                return None
            t = threading.Thread(target=self._write_snapshot,
                                 args=(snap, extra, mark), daemon=True,
                                 name="snapshot-writer")
            self._snap_thread = t
            t.start()
            with self._stats_l:
                self.stats["background_snapshots"] += 1
            return t

    def snapshot(self, store) -> None:
        """Synchronous snapshot (shutdown, snapshot-install reseed,
        tests): waits out any in-flight background writer, then writes
        inline."""
        self.wait_idle()
        with self._trigger_l:
            snap = store.snapshot()
            extra = self.extra_provider() \
                if self.extra_provider is not None else None
            mark = self.log.size()
        self._write_snapshot(snap, extra, mark)

    def wait_idle(self, timeout_s: float = 30.0) -> None:
        """Join an in-flight background snapshot writer (shutdown)."""
        with self._trigger_l:
            t = self._snap_thread
        if t is not None and t.is_alive():
            t.join(timeout_s)

    def _write_snapshot(self, snap, extra: Optional[dict],
                        wal_mark: int) -> None:
        """Serialize + atomically publish one captured snapshot, then
        drop the WAL prefix it covers (entries appended after the
        capture survive in the tail)."""
        import time as _time
        t0 = _time.perf_counter()
        try:
            with self._snap_l:
                if wal_mark < self._published_mark:
                    # a newer capture already published while this one
                    # waited: replacing it would pair an OLDER snapshot
                    # with a MORE-truncated WAL and lose the gap
                    return
                data = snap.dump_columnar() if self.columnar \
                    else snap.dump()
                if extra is not None:
                    data["extra"] = extra
                blob = msgpack.packb(data, use_bin_type=True)
                tmp = self.snapshot_path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(blob)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.snapshot_path)
                self.log.truncate_prefix(wal_mark)
                self._published_mark = wal_mark
                with self._stats_l:
                    self.stats["snapshots"] += 1
                    self.stats["last_snapshot_s"] = \
                        _time.perf_counter() - t0
                    self.stats["last_snapshot_format"] = \
                        int(data.get("format", 1))
                try:
                    self.save_cost_model()
                except OSError:     # pragma: no cover — best effort
                    pass
        except Exception:           # pragma: no cover — a failed
            # snapshot must not kill the applier or the writer thread;
            # the WAL keeps everything, the next threshold retries
            import logging
            logging.getLogger("nomad_tpu.persistence").exception(
                "snapshot write failed")
            with self._stats_l:
                self.stats["snapshot_errors"] += 1
