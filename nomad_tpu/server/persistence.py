"""Server persistence: write-ahead log + state snapshots.

Reference semantics: the Raft log (raft-boltdb) + FSM snapshots
(nomad/fsm.go Snapshot:1360 persists every table, Restore:1374 rebuilds
memdb; nomad/server.go:1214 setupRaft). Single-node round 1: the log is
an append-only file of msgpack-framed (index, type, payload) entries
written BEFORE the FSM applies them (WAL discipline); snapshots dump the
whole store and truncate the log. Restore = load snapshot + replay the
log tail. The encode/decode schema per apply type lives here so a
replicated log can reuse it unchanged.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Any, BinaryIO, Dict, List, Optional, Tuple

import msgpack

from ..models import (Allocation, Deployment, Evaluation, Job, Node,
                      SchedulerConfiguration)
from ..models.alloc import DesiredTransition
from ..models.deployment import DeploymentStatusUpdate
from ..models.node import DrainStrategy
from ..utils.codec import from_wire, to_wire

# payload field -> model type (list-wrapped == repeated)
SCHEMAS: Dict[str, Dict[str, Any]] = {
    "job_register": {"job": Job, "evals": [Evaluation]},
    "job_deregister": {"evals": [Evaluation]},
    "eval_update": {"evals": [Evaluation]},
    "eval_delete": {},
    "node_register": {"node": Node},
    "node_deregister": {},
    "node_status_update": {"evals": [Evaluation]},
    "node_eligibility_update": {},
    "node_drain_update": {"drain_strategy": DrainStrategy},
    "alloc_client_update": {"allocs": [Allocation], "evals": [Evaluation]},
    "plan_results": {"allocs_stopped": [Allocation],
                     "allocs_placed": [Allocation],
                     "allocs_preempted": [Allocation],
                     "deployment": Deployment,
                     "deployment_updates": [DeploymentStatusUpdate],
                     "evals": [Evaluation]},
    # group-commit applier: one entry carrying N plan_results payloads
    # (encode/decode recurse per group member — see below)
    "plan_group_results": {},
    "scheduler_config": {"config": SchedulerConfiguration},
    "deployment_status_update": {"update": DeploymentStatusUpdate,
                                 "job": Job, "evals": [Evaluation]},
    "deployment_promotion": {"evals": [Evaluation]},
    "alloc_desired_transition": {"transition": DesiredTransition,
                                 "evals": [Evaluation]},
    "job_stability": {},
    "scaling_event": {},
    "server_membership": {},
    "noop": {},
    "deployment_delete": {},
    "periodic_launch": {},
}


def _register_acl_schemas() -> None:
    # deferred: nomad_tpu.acl imports jobspec which imports models —
    # registering lazily avoids a cycle at module import time
    from ..acl import AclPolicy, AclToken
    from ..models.csi import CSIVolume
    SCHEMAS.update({
        "acl_policy_upsert": {"policies": [AclPolicy]},
        "acl_policy_delete": {},
        "acl_token_upsert": {"tokens": [AclToken]},
        "acl_token_delete": {},
        "csi_volume_register": {"volumes": [CSIVolume]},
        "csi_volume_deregister": {},
        "csi_volume_claim": {},
        "csi_volume_release": {},
    })
    from .event_sink import EventSink
    SCHEMAS.update({
        "event_sink_upsert": {"sink": EventSink},
        "event_sink_delete": {},
        "event_sink_progress": {},
    })
    from ..models.services import ServiceRegistration
    SCHEMAS.update({
        "service_registration_upsert": {"services": [ServiceRegistration]},
        "service_registration_delete": {},
    })
    from ..models.namespace import Namespace
    SCHEMAS.update({
        "namespace_upsert": {"namespaces": [Namespace]},
        "namespace_delete": {},
    })


_register_acl_schemas()


def encode_payload(msg_type: str, payload: dict) -> dict:
    if msg_type == "plan_group_results":
        return {"groups": [encode_payload("plan_results", g)
                           for g in payload.get("groups", [])]}
    out = {}
    for k, v in payload.items():
        out[k] = to_wire(v)
    return out


def decode_payload(msg_type: str, data: dict) -> dict:
    if msg_type == "plan_group_results":
        return {"groups": [decode_payload("plan_results", g)
                           for g in data.get("groups", [])]}
    schema = SCHEMAS.get(msg_type, {})
    out: dict = {}
    for k, v in data.items():
        hint = schema.get(k)
        if hint is None:
            out[k] = v
        elif isinstance(hint, list):
            out[k] = [from_wire(hint[0], x) for x in (v or [])]
        else:
            out[k] = from_wire(hint, v) if v is not None else None
    return out


class RaftLog:
    """Append-only WAL of msgpack frames: [u32 length][payload]."""

    def __init__(self, path: str):
        self.path = path
        self._l = threading.Lock()
        self._f: Optional[BinaryIO] = None
        self._good_offset: Optional[int] = None

    def open(self) -> None:
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        # a torn tail from a crash must be truncated before appending,
        # or the garbage bytes poison every later frame on next replay
        if self._good_offset is not None and os.path.exists(self.path) \
                and os.path.getsize(self.path) > self._good_offset:
            with open(self.path, "r+b") as f:
                f.truncate(self._good_offset)
        self._f = open(self.path, "ab")

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None

    def append(self, index: int, msg_type: str, payload: dict) -> None:
        import time as _time
        frame = msgpack.packb(
            {"i": index, "t": msg_type, "ts": _time.time(),
             "p": encode_payload(msg_type, payload)},
            use_bin_type=True)
        with self._l:
            self._f.write(struct.pack("<I", len(frame)))
            self._f.write(frame)
            self._f.flush()

    def replay(self) -> List[Tuple[int, str, dict]]:
        """Read all entries; tolerates a torn final frame (crash)."""
        out: List[Tuple[int, str, dict]] = []
        self._good_offset = 0
        if not os.path.exists(self.path):
            return out
        with open(self.path, "rb") as f:
            while True:
                header = f.read(4)
                if len(header) < 4:
                    break
                (length,) = struct.unpack("<I", header)
                frame = f.read(length)
                if len(frame) < length:
                    break  # torn write at crash: drop the tail
                try:
                    entry = msgpack.unpackb(frame, raw=False)
                    decoded = decode_payload(entry["t"], entry["p"])
                except Exception:
                    break  # corrupt frame: treat like a torn tail
                out.append((entry["i"], entry["t"], decoded,
                            entry.get("ts", 0.0)))
                self._good_offset = f.tell()
        return out

    def truncate(self) -> None:
        with self._l:
            if self._f:
                self._f.close()
            self._f = open(self.path, "wb")


class Persistence:
    """Snapshot + WAL pair under a data directory."""

    SNAPSHOT = "state.snap"
    WAL = "raft.log"
    # measured per-(arm, n_pad) dispatch costs (ops/select.py
    # DispatchCostModel.snapshot() format: {"<arm>@<n_pad>":
    # {"ewma_s": float, "samples": int}}), persisted as JSON next to
    # the state snapshot so a restarted server's routing/batching
    # decisions start measured instead of cold (ISSUE 7). Host+device
    # local by construction — never replicated, safe to delete
    COST_MODEL = "cost_model.json"

    def __init__(self, data_dir: str, snapshot_every: int = 1024):
        self.data_dir = data_dir
        self.snapshot_every = snapshot_every
        self.log = RaftLog(os.path.join(data_dir, self.WAL))
        self._since_snapshot = 0
        self._l = threading.Lock()
        # server-level state (e.g. the GC TimeTable) rides along in the
        # snapshot under "extra"; the provider is set by the Server
        self.extra_provider = None
        # set by the Server: returns the live cost-model snapshot dict;
        # written on every state snapshot and at shutdown
        self.cost_model_provider = None
        self.restored_extra: dict = {}

    @property
    def snapshot_path(self) -> str:
        return os.path.join(self.data_dir, self.SNAPSHOT)

    @property
    def cost_model_path(self) -> str:
        return os.path.join(self.data_dir, self.COST_MODEL)

    def load_cost_model(self) -> dict:
        import json
        try:
            with open(self.cost_model_path) as f:
                data = json.load(f)
            return data if isinstance(data, dict) else {}
        except (OSError, ValueError):
            return {}

    def save_cost_model(self) -> None:
        import json
        if self.cost_model_provider is None:
            return
        snap = self.cost_model_provider()
        if not snap:
            return
        tmp = self.cost_model_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f, indent=0, sort_keys=True)
        os.replace(tmp, self.cost_model_path)

    def restore_into(self, store) -> int:
        """Load snapshot + replay WAL into the store. Returns the highest
        applied index (0 if fresh)."""
        highest = 0
        if os.path.exists(self.snapshot_path):
            with open(self.snapshot_path, "rb") as f:
                data = msgpack.unpackb(f.read(), raw=False,
                                       strict_map_key=False)
            # snapshot index tuples were listified by msgpack
            self.restored_extra = data.pop("extra", {}) or {}
            store.restore(data)
            highest = store.latest_index()
        entries = self.log.replay()
        self.log.open()
        return highest, entries

    def record(self, index: int, msg_type: str, payload: dict) -> None:
        self.log.append(index, msg_type, payload)

    def maybe_snapshot(self, store) -> None:
        """Called AFTER the FSM applied the entry — a snapshot taken here
        includes it, so truncating the log is safe."""
        with self._l:
            self._since_snapshot += 1
            if self._since_snapshot < self.snapshot_every:
                return
            self._since_snapshot = 0
        self.snapshot(store)

    def snapshot(self, store) -> None:
        data = store.dump()
        if self.extra_provider is not None:
            data["extra"] = self.extra_provider()
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(data, use_bin_type=True))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.snapshot_path)
        self.log.truncate()
        try:
            self.save_cost_model()
        except OSError:         # pragma: no cover — best effort
            pass
