"""PlanQueue: leader-side priority queue of submitted plans.

Reference semantics: nomad/plan_queue.go — Enqueue:95 returns a future
the worker blocks on; Dequeue:126 pops highest priority for the applier.
"""

from __future__ import annotations

import heapq
from concurrent.futures import Future
from typing import List, Optional, Tuple

from ..models import Plan
from ..rpc.codec import RpcRefused
from ..utils.locks import make_condition


class PendingPlan:
    __slots__ = ("plan", "future", "enqueued_t")

    def __init__(self, plan: Plan):
        import time
        self.plan = plan
        self.future: Future = Future()
        # flight recorder (ISSUE 9): the applier stamps this plan's
        # queue wait onto its verify span — under load the gap between
        # Process() ending and verification starting IS the plan
        # queue, and a sum can't show which eval paid it
        self.enqueued_t = time.monotonic()


class PlanQueue:
    def __init__(self):
        self._l = make_condition()
        self._enabled = False
        self._heap: List[Tuple[int, int, PendingPlan]] = []
        self._seq = 0
        # scheduler-plane accounting (ISSUE 16): remote plans arrive
        # through Plan.Submit and mix with local ones in this heap —
        # the split shows whether the cluster plane is actually feeding
        # the applier or the leader is scheduling alone
        self.stats = {"enqueued": 0, "enqueued_remote": 0}

    def set_enabled(self, enabled: bool) -> None:
        with self._l:
            self._enabled = enabled
            if not enabled:
                for _, _, pending in self._heap:
                    pending.future.set_exception(
                        RpcRefused("plan queue is disabled"))
                self._heap.clear()
            self._l.notify_all()

    def enqueue(self, plan: Plan, remote: bool = False) -> Future:
        with self._l:
            if not self._enabled:
                # stepdown refusal: the submitting worker nacks and the
                # new leader's rebuilt broker redelivers — protocol,
                # not a scheduler fault
                raise RpcRefused("plan queue is disabled")
            pending = PendingPlan(plan)
            self._seq += 1
            heapq.heappush(self._heap, (-plan.priority, self._seq, pending))
            self.stats["enqueued"] += 1
            if remote:
                self.stats["enqueued_remote"] += 1
            self._l.notify_all()
            return pending.future

    def dequeue(self, timeout_s: Optional[float] = None) -> Optional[PendingPlan]:
        group = self.dequeue_group(1, timeout_s)
        return group[0] if group else None

    def dequeue_group(self, max_n: int,
                      timeout_s: Optional[float] = None
                      ) -> List[PendingPlan]:
        """Group drain for the group-commit applier: block (up to
        timeout_s) for the first plan, then take every plan already
        queued — up to max_n total — WITHOUT waiting for more. Plans
        come off in priority order, exactly the order the one-at-a-time
        dequeue would have served them; a plan arriving after the drain
        simply leads the next group. Returns [] on timeout."""
        import time
        deadline = (time.monotonic() + timeout_s) if timeout_s else None
        with self._l:
            while not self._heap:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return []
                self._l.wait(remaining if remaining is not None else 1.0)
            out: List[PendingPlan] = []
            while self._heap and len(out) < max_n:
                out.append(heapq.heappop(self._heap)[2])
            return out

    def depth(self) -> int:
        with self._l:
            return len(self._heap)
