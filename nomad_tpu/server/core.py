"""The server: state store + FSM apply + broker + plan pipeline + workers.

Reference semantics: nomad/server.go (NewServer:295, setupWorkers:1438),
nomad/fsm.go (the ~45 log-type dispatch collapses to the raft_apply
switch here), nomad/leader.go (establishLeadership:222 — broker/blocked/
plan-queue enablement, restoreEvals:496, reapFailedEvaluations:766),
nomad/heartbeat.go (TTL timers -> node down -> createNodeEvals,
node_endpoint.go:1318).

Round-1 consensus: a single-node raft shim (monotonic index + serialized
apply). The FSM surface is kept narrow and explicit so a replicated log
can replace `raft_apply` without touching callers.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..models import (
    Allocation, Evaluation, Job, Node,
    EVAL_STATUS_FAILED, EVAL_STATUS_PENDING,
    JOB_STATUS_PENDING, JOB_STATUS_RUNNING,
    JOB_TYPE_CORE, JOB_TYPE_SERVICE, JOB_TYPE_SYSTEM,
    NODE_STATUS_DOWN, NODE_STATUS_READY,
    TRIGGER_JOB_DEREGISTER, TRIGGER_JOB_REGISTER, TRIGGER_NODE_UPDATE,
)
from ..models.evaluation import (
    CORE_JOB_DEPLOYMENT_GC, CORE_JOB_EVAL_GC, CORE_JOB_FORCE_GC,
    CORE_JOB_JOB_GC, CORE_JOB_NODE_GC, TRIGGER_SCHEDULED,
)
from ..state import StateStore
from ..utils import metrics
from ..utils.timetable import TimeTable
from .blocked_evals import BlockedEvals
from .deployment_watcher import (
    DeploymentsWatcher, fail_deployment, pause_deployment,
    promote_deployment,
)
from .drainer import NodeDrainer, drain_allocs
from .eval_broker import EvalBroker, FAILED_QUEUE
from .event_broker import EventBroker, events_from_apply
from .periodic import PeriodicDispatch
from .plan_applier import PlanApplier
from .plan_queue import PlanQueue
from .worker import Worker
from ..utils.locks import make_lock, make_rlock

CORE_JOB_PRIORITY = 200  # structs.go CoreJobPriority = 2 * JobMaxPriority

LOG = logging.getLogger("nomad_tpu.server")


@dataclass
class ServerConfig:
    num_schedulers: int = 2
    enabled_schedulers: tuple = ("service", "batch", "system")
    # this server's federation region (nomad/config.go Region); requests
    # stamped with a foreign region forward to that region's agent
    region: str = "global"
    # federation peers: region name -> that region's agent HTTP address
    # (the reference discovers via WAN serf; here configured)
    region_peers: dict = field(default_factory=dict)
    # ACL/namespace replication source (nomad/config.go
    # AuthoritativeRegion + ReplicationToken): non-authoritative
    # leaders replicate policies, GLOBAL tokens, and namespaces from it
    authoritative_region: str = ""
    replication_token: str = ""
    # max READY evals one worker drains into a single batched dispatch
    # (SURVEY §2.6 row 1; 1 disables batching). DEFAULT 1: measured on
    # real TPU at C2M scale, concurrent workers overlapping device
    # round trips (decorrelated solo dispatches) beat coalescing lanes
    # into one vmapped dispatch (BENCH r5: stream 10.0k/s solo vs
    # 6.5k/s batched — the mega-dispatch serializes lane host work
    # under the GIL). The gateway stays available for queue-depth
    # regimes where dispatch slots, not host time, are the bottleneck.
    eval_batch_size: int = 1
    # driver/config for injected connect proxy tasks (the reference
    # hardcodes docker+envoy, job_endpoint_hook_connect.go:23)
    connect_sidecar_driver: str = "docker"
    connect_sidecar_config: Optional[dict] = None
    # GC safepoints (server/worker.py): disable automatic CPython
    # collection and collect young gens between evals, keeping
    # collector pauses out of scheduling latency. Process-wide side
    # effect, so off by default; the CLI agent turns it on.
    gc_safepoints: bool = False
    heartbeat_ttl_s: float = 10.0
    # cluster rollup staleness (ISSUE 13): a node whose heartbeat
    # host-stats payload is older than this counts as a stale
    # heartbeat in the cluster.* series and drops out of the fleet
    # used-vs-allocated economics (its capacity still counts)
    stats_stale_after_s: float = 30.0
    failed_eval_unblock_delay_s: float = 60.0
    dev_mode: bool = True
    data_dir: str = ""              # empty == in-memory only
    snapshot_every: int = 1024      # WAL entries between snapshots
    # columnar snapshot & cold-start recovery pipeline (ISSUE 8,
    # server/persistence.py + state/columnar.py):
    # write format-2 columnar snapshots (struct-of-arrays framed in
    # msgpack) instead of the legacy per-object dump; restore reads
    # BOTH formats regardless, so flipping this is always safe
    snapshot_columnar: bool = True
    # serialize snapshots on a background thread off an O(1) MVCC
    # store snapshot — maybe_snapshot only triggers, the applier never
    # blocks on a dump of a large store
    snapshot_background: bool = True
    # WAL durability: fsync appends (False matches the pre-r12
    # flush-only behavior — tests and benches stay fast); with fsync
    # on, wal_group_fsync pays ONE fsync per committed apply batch
    # (the raft FSM batch / dev-mode entry) instead of one per frame
    wal_fsync: bool = False
    wal_group_fsync: bool = True
    # GC cadence + retention (nomad/config.go *GCInterval/*GCThreshold)
    gc_interval_s: float = 60.0
    eval_gc_threshold_s: float = 3600.0
    job_gc_threshold_s: float = 4 * 3600.0
    node_gc_threshold_s: float = 24 * 3600.0
    deployment_gc_threshold_s: float = 3600.0
    # ACL subsystem (nomad/config.go ACLEnabled)
    acl_enabled: bool = False
    # autopilot dead-server cleanup (nomad/autopilot.go): a voter with
    # no replication contact for this long is removed from the member
    # set; 0 disables
    dead_server_cleanup_s: float = 60.0
    # lease TTL for derived vault tokens (vault.go ttl on CreateToken);
    # clients renew at ttl/2 via Node.RenewVaultToken
    vault_token_ttl_s: float = 3600.0
    # steady-state governor (governor/): accounting cadence, watermark
    # levels for the pressure gauges, and structure bounds. Levels are
    # deliberately high — backpressure is an overload valve, not a
    # scheduler tune
    governor_enabled: bool = True
    governor_interval_s: float = 1.0
    governor_broker_depth_high: int = 8192
    governor_plan_depth_high: int = 256
    governor_p99_high_ms: float = 1000.0
    # p99 watermark needs a WARM, populated latency reservoir before
    # it means anything — a fresh agent's first evals carry
    # multi-second JIT compiles that must not engage backpressure
    # (r6 e2e verify). Gates on observed LATENCIES, not uptime, and
    # MUST exceed Governor.P99_WINDOW (512): the gauge reads the most
    # recent 512 samples, so anything smaller opens the gauge while
    # the compile-era latencies still sit inside the p99 window
    governor_p99_min_samples: int = 640
    governor_version_debt_high: int = 100_000
    # byte watermark for early event-history shedding; the ring's own
    # count/byte caps are the hard bound, this is the soft one (0 =
    # disabled: never truncate below the ring's own caps)
    governor_event_bytes_high: int = 12 << 20
    # 0 = derive from the shape-LRU bound (2 caches x KERNEL_CACHE_MAX
    # + slack for jax's internal per-function caches)
    governor_kernel_cache_high: int = 0
    # device-resident node table (ops/device_table.py): scattered-row
    # debt that triggers the fold-to-rebuild reclaim (one contiguous
    # re-upload replacing the scatter history)
    governor_table_delta_debt_high: int = 200_000
    # backpressure escalation: when the broker's delayed/requeue heap
    # itself crosses this depth, the HTTP job-register path starts
    # returning 429 + Retry-After (0 disables)
    governor_broker_delayed_high: int = 16384
    # pipelined worker loop: eval N's ack-side bookkeeping overlaps
    # eval N+1's host phase, and the resident table's device scatter
    # is dispatched right after the snapshot fence
    worker_pipeline: bool = True
    # group-commit plan applier (plan_applier.py): max queued plans
    # drained into ONE overlay-aware verify pass + ONE raft entry +
    # ONE state-store transaction + ONE event flush. 1 restores the
    # one-entry-per-plan pipeline; the NOMAD_TPU_PLAN_GROUP=0 env
    # kill switch forces that at runtime (bisection)
    plan_group_max: int = 32
    # intra-group conflict demotions in the applier's 10s window above
    # this shrink the group bound (reclaim halves it; a clean streak
    # re-widens) instead of letting demoted plans thrash verify-retry
    # round trips
    governor_plan_group_conflict_high: int = 64
    # columnar reconcile engine (state/alloc_index.py +
    # scheduler/reconcile_columnar.py): the per-job struct-of-arrays
    # alloc index the reconciler's masks read. False disables index
    # maintenance and the schedulers fall back to the reference
    # per-alloc reconciler (NOMAD_TPU_COLUMNAR_RECONCILE=0 is the
    # runtime kill switch for bisection)
    reconcile_columnar: bool = True
    # bound on live per-job index entries (FIFO eviction)
    reconcile_index_max_jobs: int = 512
    # pending write-through deltas beyond this drop the entry — a cold
    # job nobody reconciles must not hoard a delta log; the next read
    # rebuilds dense
    reconcile_index_delta_max: int = 4096
    # total pending columnar-index delta debt across jobs: crossing it
    # folds the index back to dense rebuild (governor reclaim)
    governor_reconcile_index_debt_high: int = 65536
    # adaptive micro-batch eval dispatch (server/worker.py
    # MicroBatchGateway): concurrent evals' kernel requests accumulate
    # for up to this window and ship as ONE vmapped padded device call.
    # The live window adapts off the per-lane arrival-rate EWMA
    # (idle lanes dispatch immediately) and queue depth (see
    # governor_gateway_depth_high); over a tunneled accelerator the
    # base widens to half the measured RTT. 0 disables the gateway
    # entirely (exactly the pre-gateway dispatch path);
    # NOMAD_TPU_MICROBATCH=0 is the runtime kill switch
    gateway_window_us: int = 2000
    # occupancy trigger: a lane holding this many parked requests
    # fires without waiting out the window
    gateway_min_batch: int = 4
    # broker READY depth above which the gateway widens its window
    # (occupancy over per-eval latency while a backlog exists; decays
    # back once the queue drains). The governor's READY-depth
    # watermark reclaim also widens it directly
    governor_gateway_depth_high: int = 512
    # startup calibration probe (ops/select.calibrate_cost_model):
    # measure the solo + batched dispatch arms at the restored table
    # shape and seed the dispatch cost model, so batched lanes are
    # cost-favored from the first dispatch instead of after 3+
    # organic samples. Pays two XLA compiles at start, so off by
    # default; the CLI agent and the benches turn it on
    dispatch_calibration: bool = False
    # batched columnar preemption (scheduler/preemption.py): victim
    # selection for all candidate nodes runs as ONE struct-of-arrays
    # pass + vectorized greedy instead of a per-node Python Preemptor.
    # False restores the per-node reference path everywhere
    # (NOMAD_TPU_COLUMNAR_PREEMPT=0 is the runtime kill switch)
    preempt_columnar: bool = True
    # candidate-matrix row cap: a node with more eligible candidate
    # allocs than this takes the per-node reference path instead of
    # padding every other node's matrix row to its width
    preempt_rows_max: int = 4096
    # victim-set memo bound (NodeTable.preempt_cache); crossing it
    # clears the memo wholesale — the governor watermark below
    # reclaims earlier and gradually
    preempt_cache_max: int = 200_000
    # watermark on live victim-memo entries (each pins a live-alloc
    # row + its victim allocs); crossing it drops the memo via the
    # governor reclaim (preemption.victim_cache_entries gauge)
    governor_preempt_cache_high: int = 150_000
    # compiled feasibility engine (scheduler/feasible_compiler.py +
    # state/node_attr_index.py, ISSUE 17): constraint trees compile to
    # predicate programs over interned node-attribute columns; False
    # restores the per-node scalar checks everywhere
    # (NOMAD_TPU_COLUMNAR_FEAS=0 is the runtime kill switch)
    feas_columnar: bool = True
    # distinct-value cap per interned attribute column: a column
    # exceeding it (near-unique values — ids, addresses) flags
    # overflow and its constraints take the scalar path, keeping
    # verdict LUTs small
    feas_intern_max_values: int = 4096
    # compiled-program/mask cache bound (FIFO past it); the governor
    # watermark below reclaims masks earlier and keeps intern tables
    feas_mask_cache_max: int = 256
    # watermark on live mask-cache entries (each pins bool[N] rows per
    # static check); crossing it drops cached masks via the governor
    # reclaim but KEEPS the intern tables — the next eval rebuilds
    # masks from columns, not columns from nodes
    governor_feas_mask_cache_high: int = 192
    # residue-compiled feasibility (ISSUE 20): CSI-claim/quota/
    # preferred-node residue rides the device-resident mask as a
    # sparse per-eval scatter (the FeasMaskStore token survives
    # residue mutations), device inventory checks only flagged rows,
    # and spread/distinct scoring inputs build vectorized over the
    # interned columns; False restores the dense re-upload + per-node
    # walks (NOMAD_TPU_FEAS_RESIDUE=0 is the runtime kill switch)
    feas_residue: bool = True
    # watermark on accumulated residue-scatter rows atop the parked
    # device masks; crossing it folds the FeasMaskStore (drops parked
    # entries) so the next eval re-parks a fresh combined mask instead
    # of compounding per-eval scatter debt
    governor_feas_residue_high: int = 262_144
    # eval flight recorder (nomad_tpu/trace/): always-on per-eval span
    # tracing — enqueue -> gateway -> kernel -> group commit -> ack —
    # with a byte-bounded completed-trace ring, pinned tail exemplars,
    # and per-stage percentile reservoirs. Surfaced at
    # /v1/operator/trace and `nomad operator trace [-o chrome]`;
    # NOMAD_TPU_TRACE=0 is the kill switch
    trace_ring_bytes: int = 4 << 20
    # pinned exemplar slots: evals whose full enqueue->ack latency
    # clears the adaptive threshold keep their whole span tree plus a
    # governor-gauge snapshot (worst-K retention; drift findings
    # auto-pin the current set)
    trace_exemplar_slots: int = 8
    # promotion threshold as a percent of the governor-tracked
    # full-latency p99 (100 = promote anything at/above p99)
    trace_exemplar_threshold_pct: float = 100.0
    # retained telemetry collector (nomad_tpu/telemetry/, ISSUE 11):
    # background sampling cadence for the history ring behind
    # /v1/operator/telemetry, /v1/operator/flatness, and `nomad
    # operator top`. 0 disables the collector entirely (snapshot-only
    # /v1/metrics, flatness route reports disabled);
    # NOMAD_TPU_TELEMETRY=0 is the runtime kill switch
    telemetry_sample_interval_s: float = 1.0
    # history ring depth: slots per series (struct-of-arrays float64
    # columns; with the 256-series cap the ring's hard byte ceiling is
    # slots x 256 x 8 bytes — 1 MiB at the default 512)
    telemetry_ring_slots: int = 512
    # mesh-sharded resident node table (parallel/sharded_table.py):
    # keep the hot columns sharded-resident across evals when mesh
    # routing is active (NOMAD_TPU_MESH). Off falls back to the
    # capacity-only per-eval upload path; NOMAD_TPU_MESH_RESIDENT=0 is
    # the runtime kill switch and wins over this knob
    mesh_resident: bool = True
    # scattered-row debt on the mesh-resident table that triggers the
    # fold-to-rebuild reclaim (one contiguous sharded re-upload
    # replacing the scatter history) — the mesh analog of
    # governor_table_delta_debt_high
    mesh_reshard_debt_high: int = 500_000
    # runtime deadlock & race sanitizer (analysis/race.py via the
    # utils/locks.py factory, ISSUE 14): a lock held at/beyond this
    # long keeps a worst-K exemplar (stack at release) in the `locks`
    # block of /v1/operator/governor — the worst holders are exactly
    # the sites that serialize the fleet under contention. The shims
    # themselves only exist for locks constructed under
    # NOMAD_TPU_RACE=1; these knobs tune the process-global monitor
    race_lock_hold_warn_ms: float = 50.0
    # worst-holder exemplar slots retained (sorted by hold time)
    race_exemplar_slots: int = 8
    # findings ring bound (lock-order cycles, self-deadlocks,
    # unguarded mutations) — dedup by site keeps this small anyway
    race_max_findings: int = 256
    # scenario matrix + fault injection (nomad_tpu/chaos/, ISSUE 15):
    # default seed for injected fault schedules when a chaos cell
    # doesn't pin its own (0 = the matrix derives one per cell); the
    # hook points themselves cost one module-bool read per site and
    # are inert until a cell installs a FaultInjector
    chaos_seed: int = 0
    # bound within which cluster.nodes_down / stale_heartbeats must
    # reflect an injected failure — the failure-visibility invariant's
    # deadline (chaos/invariants.py)
    chaos_visibility_bound_s: float = 15.0
    # distributed scheduler plane (server/follower_sched.py, ISSUE 16):
    # when clustered, followers run worker pools against their LOCAL
    # replicated store, dequeuing evals from the leader's broker over
    # RPC and submitting plans back for leader-only verify/commit.
    # Off = leader schedules alone (the pre-plane topology);
    # NOMAD_TPU_FOLLOWER_SCHED=0 is the runtime kill switch
    follower_sched: bool = True
    # leader-side lease on a remotely dequeued eval: a dead follower's
    # evals return to READY after this long (with zero re-enqueue
    # delay — the follower failed, not the eval), instead of waiting
    # out the broker's full 60 s unack timer
    follower_lease_s: float = 30.0
    # follower-side snapshot fence budget: how long a follower worker
    # waits for local raft catch-up to reach the eval's modify index
    # before NACKing it back (a lagging replica must not schedule from
    # the past, and must not silently drop the eval either)
    follower_fence_timeout_s: float = 5.0
    # remote worker pool size per follower
    follower_max_remote: int = 2
    # batched write ingest (server/ingest.py, ISSUE 19): job registers,
    # client alloc-status updates and desired-transition writes that
    # arrive while a raft apply is in flight park and land as ONE
    # `ingest_batch` entry / store transaction / event flush. Entries
    # per batch cap:
    ingest_batch_max: int = 64
    # coalescing window (microseconds) a lone streaming write waits for
    # companions; governor reclaim halves it under queue pressure, a
    # clean streak re-widens it. <0 disables the gateway entirely (the
    # one-entry-per-write path); NOMAD_TPU_INGEST_BATCH=0 is the
    # runtime kill switch
    ingest_window_us: float = 200.0
    # queued-write depth at which check_admission sheds new writes with
    # 429/Retry-After BEFORE body decode (the byte watermark derives
    # from this: depth x 64 KiB)
    ingest_queue_high: int = 256
    # governor watermark on ingest.queue_depth that fires the
    # shrink_window reclaim (distinct from the shed watermark above —
    # the governor reclaims well before the edge starts refusing)
    governor_ingest_queue_high: int = 64


class Server:
    def __init__(self, config: Optional[ServerConfig] = None):
        self.config = config or ServerConfig()
        self.store = StateStore()
        self.store.alloc_index.enabled = self.config.reconcile_columnar
        self.store.alloc_index.max_jobs = \
            self.config.reconcile_index_max_jobs
        self.store.alloc_index.delta_max = \
            self.config.reconcile_index_delta_max
        # batched columnar preemption knobs (module-level, the
        # store.alloc_index idiom — the scheduler has no ServerConfig)
        from ..scheduler import preemption as _preemption
        _preemption.configure(columnar=self.config.preempt_columnar,
                              rows_max=self.config.preempt_rows_max,
                              cache_max=self.config.preempt_cache_max)
        # compiled feasibility knobs (module-level, same idiom); the
        # env kill switch NOMAD_TPU_COLUMNAR_FEAS wins inside enabled()
        from ..scheduler import feasible_compiler as _feas
        _feas.configure(
            enabled=self.config.feas_columnar,
            intern_max_values=self.config.feas_intern_max_values,
            mask_cache_max=self.config.feas_mask_cache_max,
            residue=self.config.feas_residue)
        self.store.attr_index.enabled = self.config.feas_columnar
        # mesh-sharded residency knob (module-level, same idiom — the
        # process-wide ShardedSelect has no ServerConfig); the env kill
        # switch NOMAD_TPU_MESH_RESIDENT wins inside resident_enabled()
        from ..parallel import sharded_table as _sharded_table
        _sharded_table.configure(resident=self.config.mesh_resident)
        # runtime race sanitizer knobs (module-level, same idiom —
        # the lock shims are process-global)
        from ..analysis import race as _race
        _race.configure(
            hold_warn_ms=self.config.race_lock_hold_warn_ms,
            exemplar_slots=self.config.race_exemplar_slots,
            max_findings=self.config.race_max_findings)
        # chaos fault-injection knobs (module-level, same idiom — the
        # injector hook points are process-global; ISSUE 15)
        from ..chaos import faults as _chaos_faults
        _chaos_faults.configure(
            seed=self.config.chaos_seed,
            visibility_bound_s=self.config.chaos_visibility_bound_s)
        # RLock: FSM appliers can nest (e.g. a node-register unblocking a
        # blocked eval re-enters raft_apply on the same thread)
        self._raft_l = make_rlock()
        self._raft_index = 10
        self.eval_broker = EvalBroker()
        # backpressure escalation threshold lives on the broker even
        # with the governor off — the HTTP register path reads it
        self.eval_broker.delayed_depth_high = \
            self.config.governor_broker_delayed_high
        self.blocked_evals = BlockedEvals(self._unblock_enqueue)
        self.plan_queue = PlanQueue()
        self.plan_applier = PlanApplier(self.plan_queue, self)
        # distributed scheduler plane (ISSUE 16): the lease table is
        # the leader-side half (remote-dequeue leases + cluster_sched
        # counters, empty on non-leaders); the follower half is built
        # in attach_raft — dev-mode servers never construct one
        from .follower_sched import EvalLeaseTable
        self.eval_leases = EvalLeaseTable(self)
        self.follower_sched = None
        self.time_table = TimeTable()
        self.periodic = PeriodicDispatch(self)
        self.deployments_watcher = DeploymentsWatcher(self)
        self.node_drainer = NodeDrainer(self)
        self.events = EventBroker()
        from .event_sink import EventSinkManager
        self.event_sinks = EventSinkManager(self)
        # adaptive micro-batch eval dispatch (ISSUE 7): one gateway per
        # server — every worker's (and every lane thread's) kernel
        # dispatches coalesce here. window=0 and the env kill switch
        # both mean NO gateway object, so the worker path degenerates
        # exactly to the pre-gateway one
        import os as _os
        self.gateway = None
        if self.config.gateway_window_us > 0 and \
                _os.environ.get("NOMAD_TPU_MICROBATCH", "1") \
                not in ("0", "off"):
            from .worker import MicroBatchGateway
            self.gateway = MicroBatchGateway(
                window_us=self.config.gateway_window_us,
                min_batch=self.config.gateway_min_batch,
                depth_fn=lambda: self.eval_broker.stats.total_ready,
                depth_high=self.config.governor_gateway_depth_high)
        # batched write ingest (ISSUE 19): the write-side twin of the
        # gateway above — same no-object degeneration under window<0
        # or the env kill switch, so every write takes the unchanged
        # one-raft-entry-per-object path
        self.ingest = None
        from .ingest import IngestGateway, ingest_batch_enabled
        if self.config.ingest_window_us >= 0 and ingest_batch_enabled():
            self.ingest = IngestGateway(
                self,
                batch_max=self.config.ingest_batch_max,
                window_us=self.config.ingest_window_us,
                queue_high=self.config.ingest_queue_high)
        self.governor = None
        if self.config.governor_enabled:
            from ..governor import Governor
            self.governor = Governor(
                interval_s=self.config.governor_interval_s)
            self._register_governor_gauges()
        # eval flight recorder (ISSUE 9): the process-wide tracer is
        # configured from this server's knobs and wired to its
        # governor — the exemplar threshold tracks the FULL-latency
        # p99 (queue wait included: what the eval experienced), each
        # promoted exemplar snapshots the gauge rows, and a drift
        # finding that names a suspect structure auto-pins the current
        # exemplar set (the ROADMAP "automatic operator debug capture"
        # item, done at the trace layer)
        from ..trace import tracer as _flight
        self.tracer = _flight
        _flight.configure(
            ring_bytes=self.config.trace_ring_bytes,
            exemplar_slots=self.config.trace_exemplar_slots,
            threshold_pct=self.config.trace_exemplar_threshold_pct)
        self._tracer_fns = None
        # one gauge-snapshot closure serves BOTH the tracer's exemplar
        # snapshots and the telemetry collector's per-slot sampling —
        # the two consumers must never silently diverge on how gauge
        # rows are read
        gauge_snapshot_fn = None
        if self.governor is not None:
            gov = self.governor
            gauge_snapshot_fn = lambda g=gov: {  # noqa: E731
                r["name"]: r["value"] for r in g.registry.rows()}
            _flight.threshold_fn = \
                lambda g=gov: g.latency_percentile_ms(99)
            _flight.gauge_fn = gauge_snapshot_fn
            # remembered so shutdown can detach THESE closures (and
            # only these — a newer server may have rebound them):
            # the module-global tracer outlives this server, and the
            # lambdas would otherwise pin the whole dead governor
            # graph (gauge closures reach broker/applier/store)
            self._tracer_fns = (_flight.threshold_fn, _flight.gauge_fn)
            gov.drift_hooks.append(self._auto_pin_exemplars)
        # retained telemetry collector (ISSUE 11): history rings over
        # governor gauges, counter rates, stage percentile reservoirs,
        # device economics, and RSS — the instrument behind
        # /v1/operator/telemetry, /v1/operator/flatness, and `nomad
        # operator top`. Kill switch (env or interval=0) builds no
        # collector: /v1/metrics degenerates to snapshot-only
        from ..telemetry import TelemetryCollector
        from ..telemetry import enabled as _telemetry_enabled
        self.telemetry = None
        if _telemetry_enabled() and \
                self.config.telemetry_sample_interval_s > 0:
            gov = self.governor
            self.telemetry = TelemetryCollector(
                interval_s=self.config.telemetry_sample_interval_s,
                slots=self.config.telemetry_ring_slots,
                gauges_fn=gauge_snapshot_fn,
                latency_fn=(None if gov is None
                            else gov.latency_percentile_ms),
                stage_fn=_flight.stage_percentiles,
                # device-mirror residency + the cluster.* rollup
                # (ISSUE 13) read through self (the table cache is
                # replaced on snapshot restore)
                extra_fn=self._telemetry_extra)
        self.workers: List[Worker] = []
        self._heartbeat_timers: Dict[str, threading.Timer] = {}
        self._hb_lock = make_lock()
        # per-node host-stats payloads carried by heartbeats (ISSUE
        # 13): node_id -> {payload..., received_at}; folded into the
        # cluster.* rollup by cluster_stats(), pruned when the node
        # record disappears
        self._node_stats: Dict[str, dict] = {}
        self._node_stats_l = make_lock()
        self._leader = False
        self._member_l = make_lock()   # join/leave RMW serialization
        # serializes enforced (-check-index) registrations: the CAS
        # check and the apply must not interleave across HTTP threads
        self._register_l = make_lock()
        self._acl_cache: Dict = {}      # (policies, index) -> compiled ACL
        self.raft = None                # multi-server consensus (raft.py)
        self.swim = None                # peer failure detection (swim.py)
        # thread-local: set on the FSM applier thread while an applier
        # runs, so nested raft_apply side effects are detected per
        # thread — an instance-wide flag would make a concurrent client
        # write on another thread look nested and silently drop it
        # (r3 advisor, medium)
        self._apply_tl = threading.local()

        # restore persisted state AFTER all subsystems exist: WAL replay
        # drives the same FSM appliers (broker/blocked are disabled until
        # leadership, so replay has no scheduling side effects, and no
        # change events publish — replay is not new history)
        self.persistence = None
        self.cold_start_stats: Dict[str, float] = {}
        if self.config.data_dir:
            from .persistence import Persistence
            self.persistence = Persistence(
                self.config.data_dir, self.config.snapshot_every,
                columnar=self.config.snapshot_columnar,
                background=self.config.snapshot_background,
                wal_fsync=self.config.wal_fsync,
                wal_group_fsync=self.config.wal_group_fsync)
            self.persistence.extra_provider = lambda: {
                "time_table": self.time_table.dump()}
            t0 = time.perf_counter()
            highest, entries = self.persistence.restore_into(self.store)
            restore_s = time.perf_counter() - t0
            self.time_table.restore(
                self.persistence.restored_extra.get("time_table", []))
            self._raft_index = max(self._raft_index, highest)
            # cold-start pipeline (ISSUE 8): prime the resident node
            # table ONCE at the restored index — from the snapshot's
            # decoded columns when the format provides them — then let
            # the device H2D upload overlap the WAL tail replay below;
            # the first eval after recovery rides the delta path, and
            # the eagerly rebuilt alloc index (state/store.py restore)
            # keeps reconcile.index_rebuilds at zero
            table_build_s = 0.0
            if highest > 0:
                t0 = time.perf_counter()
                self.store.table_cache.prime(self.store.snapshot(),
                                             self.store.pop_cold_columns())
                table_build_s = time.perf_counter() - t0
                threading.Thread(target=self.store.table_cache
                                 .prefetch_device, daemon=True,
                                 name="table-prefetch").start()
            t0 = time.perf_counter()
            replayed = self._replay_entries(entries, highest)
            wal_replay_s = time.perf_counter() - t0
            self.cold_start_stats = {
                "restore_s": restore_s,
                "table_build_s": table_build_s,
                "wal_replay_s": wal_replay_s,
                "wal_entries_replayed": float(replayed),
                "snapshot_format": float(
                    self.persistence.stats["restore_format"]),
            }
        # event history starts HERE: restore/replay publish no events,
        # so sink progress at or below this floor has a proven gap
        self.events.epoch_floor = self._raft_index
        if self.persistence is not None:
            # measured per-(arm, n_pad) dispatch costs persist next to
            # the WAL snapshot (ISSUE 7): a restarted server routes and
            # batches off its last life's measurements instead of
            # re-learning from cold (first live sample per shape is
            # dropped — it pays this process's XLA compile)
            from ..ops.select import cost_model
            seeds = self.persistence.load_cost_model()
            if seeds:
                loaded = cost_model.load_snapshot(seeds)
                LOG.info("cost model restored: %d measured shapes",
                         loaded)
            self.persistence.cost_model_provider = cost_model.snapshot
            if self.governor is not None:
                self._register_persistence_gauges()

    # -- lifecycle -----------------------------------------------------
    def attach_raft(self, rpc_server, peers, self_addr: str = "") -> None:
        """Join a multi-server cluster: the raft node drives leadership
        (nomad/server.go setupRaft + leader.go monitorLeadership)."""
        from .raft import RaftNode
        self.raft = RaftNode(self, self_addr or rpc_server.addr,
                             list(peers), data_dir=self.config.data_dir)
        rpc_server.methods.update(self.raft.rpc_methods())
        rpc_server.raft = self.raft
        # reconcile REPLICATED membership over the static boot config:
        # a restarted server must adopt the grown/shrunk voter set its
        # WAL/snapshot recorded (and an evicted server must come back
        # inert), or its quorum math is wrong from the first election
        members = self.store.server_members()
        if members:
            self.raft.update_members(members)
        # peer-to-peer failure detection (SWIM; nomad/serf.go): every
        # member probes, not just the leader's replication threads
        from .swim import SwimDetector
        self.swim = SwimDetector(self)
        # distributed scheduler plane (ISSUE 16): the remote-dequeue
        # verb surface rides the same RPC transport raft does, and the
        # follower worker pool is built here — started by start(),
        # inert whenever this server is (or becomes) the leader
        from .follower_sched import FollowerScheduler, rpc_handlers
        rpc_server.methods.update(rpc_handlers(self))
        self.follower_sched = FollowerScheduler(self)

    def start(self) -> None:
        if self.raft is None:
            self.establish_leadership()
        else:
            self.raft.start()
            if self.swim is not None:
                self.swim.start()
            if self.follower_sched is not None:
                self.follower_sched.start()
        self.plan_applier.start()
        if self.ingest is not None:
            self.ingest.start()
        for i in range(self.config.num_schedulers):
            w = Worker(self, list(self.config.enabled_schedulers)
                       + [JOB_TYPE_CORE], wid=i)
            self.workers.append(w)
            w.start()
        self._reaper = threading.Thread(target=self._reap_failed_evals,
                                        daemon=True, name="eval-reaper")
        self._reaper.start()
        self._gc_ticker = threading.Thread(target=self._schedule_periodic_gc,
                                           daemon=True, name="gc-ticker")
        self._gc_ticker.start()
        self._stats_ticker = threading.Thread(target=self._emit_stats,
                                              daemon=True,
                                              name="stats-ticker")
        self._stats_ticker.start()
        self._volume_watcher = threading.Thread(target=self._watch_volumes,
                                                daemon=True,
                                                name="volume-watcher")
        self._volume_watcher.start()
        if self.governor is not None:
            self.governor.start()
        if self.telemetry is not None:
            self.telemetry.start()
        if self.config.dispatch_calibration:
            # seed the dispatch cost model at the restored table shape
            # BEFORE traffic: the solo and batched arms both carry
            # measured numbers from the first organic dispatch (no
            # nodes yet == nothing to calibrate; benches with
            # programmatic node seeding call calibrate_cost_model
            # themselves after seeding)
            try:
                n = self.store.node_count()
                if n >= 8:
                    from ..ops.select import calibrate_cost_model
                    calibrate_cost_model(
                        n, lanes=self.config.gateway_min_batch)
            except Exception:   # pragma: no cover — best effort
                LOG.exception("dispatch calibration failed")

    def _register_governor_gauges(self) -> None:
        """Wire every long-lived structure into the governor's
        accounting registry, with watermark policies and targeted
        reclamation where a bound exists (ISSUE r6 tentpole; the
        reference keeps these flat via core_sched GC + EmitStats)."""
        from ..governor import WatermarkPolicy
        from ..ops.select import (clear_kernel_caches,
                                  kernel_cache_entries)
        cfg = self.config
        gov = self.governor
        broker = self.eval_broker   # .stats is REPLACED on flush —
        # gauges must read through the broker, never a captured stats

        # broker queues: depth gauges; READY depth is the admission
        # signal (backpressure sheds enqueues, workers shrink lanes).
        # With the micro-batch gateway live, the watermark reclaim
        # WIDENS its dispatch window — under a backlog, batch occupancy
        # beats per-eval dispatch latency (ISSUE 7)
        gov.register("broker.ready", lambda: broker.stats.total_ready,
                     WatermarkPolicy(cfg.governor_broker_depth_high,
                                     pressure=True),
                     reclaim=(self.gateway.widen_window
                              if self.gateway is not None else None))
        gov.register("broker.unacked",
                     lambda: broker.stats.total_unacked)
        gov.register("broker.waiting",
                     lambda: broker.stats.total_waiting)
        gov.register("broker.shed", lambda: broker.stats.total_shed,
                     suspect=False)  # monotone counter, not a structure
        gov.register("blocked_evals.blocked",
                     self.blocked_evals.blocked_count)
        gov.register("plan_queue.depth", self.plan_queue.depth,
                     WatermarkPolicy(cfg.governor_plan_depth_high,
                                     pressure=True))

        # sampled service p99 from the workers' latency reservoir: the
        # primary backpressure gauge (SOAK_r05: p99 drifted 69->208 ms).
        # The gauge reports 0 until the reservoir holds enough REAL
        # latencies — gating on observed evals, not sampler uptime, so
        # an idle-then-cold-start agent can't trip it on JIT compiles
        def p99_gauge():
            if gov.latency_samples() < cfg.governor_p99_min_samples:
                return 0.0
            # recent_: a reservoir with no fresh latencies reads 0, so
            # an engaged-backpressure idle period can't latch the
            # watermark shut on frozen samples
            return gov.recent_p99_ms()
        # suspect=False: this IS the perf signal, not a structure
        # whose growth could explain it
        gov.register("service.p99_ms", p99_gauge,
                     WatermarkPolicy(cfg.governor_p99_high_ms,
                                     pressure=True),
                     unit="ms", suspect=False)

        # event broker: the ring enforces its own count+byte caps on
        # publish (the hard bound). The governor watermark is the SOFT
        # byte bound — set BELOW the ring's max_bytes so it can only
        # fire on genuine payload-byte pressure, never sit permanently
        # 'over' on a legitimately full ring of small events
        gov.register("event_broker.events", self.events.buffered_events)
        if cfg.governor_event_bytes_high > 0:
            gov.register("event_broker.bytes",
                         self.events.buffered_bytes,
                         WatermarkPolicy(cfg.governor_event_bytes_high),
                         reclaim=lambda: self.events.truncate(0.5),
                         unit="bytes")
        else:
            gov.register("event_broker.bytes",
                         self.events.buffered_bytes, unit="bytes")

        # state store: uncompacted layer-overlay debt (the version
        # chains the r5 soak showed growing between snapshots) with
        # fold compaction as the reclaim; changelog is already bounded
        # force=True: crossing the watermark IS the escalation — the
        # per-table proportional fold floor must not veto every table
        # and leave the reclaim a permanent no-op while debt grows
        gov.register("state.version_debt", self.store.version_debt,
                     WatermarkPolicy(cfg.governor_version_debt_high),
                     reclaim=lambda: self.store.compact(min_tip=1024,
                                                        force=True))
        gov.register("state.changelog", self.store.changelog_len)
        gov.register("state.allocs",
                     lambda: len(self.store._root.table("allocs")))
        gov.register("state.evals",
                     lambda: len(self.store._root.table("evals")))

        # JIT kernel caches (ops/select.py): the shape-LRUs bound
        # themselves at KERNEL_CACHE_MAX each; the watermark (derived
        # from that bound unless overridden, so NOMAD_TPU_KERNEL_CACHE_MAX
        # retunes both together) alarms on jax's unbounded internal
        # per-function caches, where the break-glass full clear is the
        # only reclaim
        from ..ops.select import KERNEL_CACHE_MAX
        kc_high = cfg.governor_kernel_cache_high or \
            (2 * KERNEL_CACHE_MAX + 512)
        gov.register("kernel_cache.entries", kernel_cache_entries,
                     WatermarkPolicy(kc_high),
                     reclaim=clear_kernel_caches)

        # resident-table identity memos (ops/tables.py): FIFO-bounded,
        # but accounted — every entry pins a resources graph
        from ..ops.tables import BUILD_STATS, resource_memo_len
        gov.register("node_table.resource_memo", resource_memo_len)

        # device-resident node table (ops/device_table.py): scattered-
        # row debt with fold-to-rebuild as the reclaim — when the
        # scatter history since the last contiguous upload crosses the
        # watermark, one full re-upload replaces it and resets the
        # delta log. Gauges read through self.store: the table cache
        # is REPLACED on snapshot restore (store.py), so captured
        # references would go stale
        gov.register("node_table.delta_debt",
                     lambda: self.store.table_cache.device_delta_debt(),
                     WatermarkPolicy(cfg.governor_table_delta_debt_high),
                     reclaim=lambda: self.store.table_cache.fold_device())
        gov.register("node_table.delta_log",
                     lambda: self.store.table_cache.device_delta_log_len())
        gov.register("node_table.full_builds",
                     lambda: BUILD_STATS["full_builds"], suspect=False)
        gov.register("node_table.delta_refreshes",
                     lambda: BUILD_STATS["delta_refreshes"],
                     suspect=False)

        # mesh-sharded resident node table (parallel/sharded_table.py):
        # device count, sharded residency footprint, and the reshard /
        # delta-scatter traffic split — `mesh.reshard_uploads` flat
        # across a warm eval run IS the zero-reupload steady state the
        # multichip bench asserts. All read through the process-wide
        # snapshot (empty dict -> 0 while no mesh dispatcher exists).
        # The scattered-row debt carries the watermark, with a
        # contiguous sharded re-upload as the reclaim (the mesh analog
        # of node_table.delta_debt's fold-to-rebuild)
        from ..ops.select import mesh_stats_snapshot

        def _mesh(key):
            return lambda: float(mesh_stats_snapshot().get(key, 0) or 0)

        gov.register("mesh.devices", _mesh("devices"), suspect=False)
        gov.register("mesh.resident_bytes_per_device",
                     _mesh("resident_bytes_per_device"))
        gov.register("mesh.reshard_uploads", _mesh("reshard_uploads"),
                     suspect=False)
        gov.register("mesh.delta_scatters", _mesh("delta_scatters"),
                     suspect=False)
        gov.register("mesh.resident_hits", _mesh("resident_hits"),
                     suspect=False)
        gov.register("mesh.reshard_debt",
                     lambda: self.store.table_cache.mesh_reshard_debt(),
                     WatermarkPolicy(cfg.mesh_reshard_debt_high),
                     reclaim=lambda: self.store.table_cache.fold_mesh())

        # backpressure escalation (ROADMAP open item): the delayed/
        # requeue heap depth — when admission deferral itself backs up,
        # the HTTP register path starts shedding with 429s
        gov.register("broker.delayed_depth", broker.delayed_depth)

        # group-commit plan applier (plan_applier.py): group sizing and
        # intra-group conflict visibility. The conflict gauge reads a
        # sliding 10s window (a monotone total would latch the
        # watermark over forever); its reclaim SHRINKS the group bound
        # so optimistic siblings stop trampling each other, and the
        # applier re-widens after a clean streak
        applier = self.plan_applier
        gov.register("plan_group.size", applier.mean_group_size,
                     suspect=False)
        gov.register("plan_group.conflict_retries",
                     applier.conflict_pressure,
                     WatermarkPolicy(
                         cfg.governor_plan_group_conflict_high),
                     reclaim=applier.shrink_group_bound, suspect=False)
        gov.register("plan_group.singleton_fallbacks",
                     lambda: applier.stats["singleton_fallbacks"],
                     suspect=False)

        # cross-eval engine host-phase reuse (scheduler/stack.py):
        # bounded keyed cache of per-(job, task-group) static state
        from ..scheduler.stack import engine_cache_entries
        gov.register("engine_cache.entries", engine_cache_entries)

        # columnar reconcile engine (state/alloc_index.py): index
        # sizing, dense rebuilds, the tasks_updated memo hit rate, and
        # pending write-through delta debt with fold-to-rebuild as the
        # reclaim. Gauges read through self.store — the cache is
        # replaced on snapshot restore
        from ..scheduler.stack import tasks_updated_hit_rate
        gov.register("reconcile.index_rows",
                     lambda: self.store.alloc_index.rows())
        gov.register("reconcile.index_rebuilds",
                     lambda: self.store.alloc_index.stats["rebuilds"],
                     suspect=False)
        gov.register("reconcile.tasks_updated_hit_rate",
                     tasks_updated_hit_rate, unit="ratio",
                     suspect=False)
        gov.register("reconcile.index_debt",
                     lambda: self.store.alloc_index.debt(),
                     WatermarkPolicy(
                         cfg.governor_reconcile_index_debt_high),
                     reclaim=lambda: self.store.alloc_index.fold())

        # batched columnar preemption (scheduler/preemption.py, ISSUE
        # 10): candidate-matrix volume, cross-eval victim-memo traffic,
        # and dirty-row invalidations — all monotone, never drift
        # suspects. The memo SIZE gauge carries the watermark: every
        # entry pins a live-alloc row list plus its victim allocs, so
        # a churning fleet must not let it grow to the hard
        # preempt_cache_max clear-all; reads go through self.store
        # (the table cache is replaced on snapshot restore)
        from ..scheduler.preemption import PREEMPT_STATS as _ps
        gov.register("preemption.candidate_rows",
                     lambda: _ps["candidate_rows"], suspect=False)
        gov.register("preemption.victim_cache_hits",
                     lambda: _ps["cache_hits"], suspect=False)
        gov.register("preemption.cache_invalidations",
                     lambda: _ps["invalidations"], suspect=False)
        gov.register("preemption.victim_cache_entries",
                     lambda: self.store.table_cache.preempt_cache_len(),
                     WatermarkPolicy(cfg.governor_preempt_cache_high),
                     reclaim=lambda:
                     self.store.table_cache.clear_preempt_cache())

        # compiled feasibility engine (scheduler/feasible_compiler.py,
        # ISSUE 17): intern-table volume, cached mask count, and the
        # steady-state hit rate. The mask-entry gauge carries the
        # watermark: each entry pins bool[N] rows per static check, so
        # the reclaim drops MASKS only — intern tables survive (the
        # next eval rebuilds masks from columns in one np.take, not
        # columns from an O(N) node walk). Reads go through
        # self.store.attr_index (replaced on snapshot restore); the
        # hit rate and recompile count are module-level like the
        # preemption stats
        from ..scheduler import feasible_compiler as _feas_mod
        gov.register("feas.intern_values",
                     lambda: self.store.attr_index.gauge_stats()
                     ["intern_values"], suspect=False)
        gov.register("feas.mask_cache_entries",
                     lambda: self.store.attr_index.gauge_stats()
                     ["mask_cache_entries"],
                     WatermarkPolicy(cfg.governor_feas_mask_cache_high),
                     reclaim=lambda: self.store.attr_index.drop_masks())
        gov.register("feas.mask_cache_hit_rate", _feas_mod.hit_rate,
                     unit="ratio", suspect=False)
        gov.register("feas.recompiles",
                     lambda: _feas_mod.stats()["recompiles"],
                     suspect=False)

        # residue-compiled feasibility (ISSUE 20): token survival vs
        # invalidation counts how often the device-resident combined
        # mask outlives a CSI/preferred-node mutation (survival = the
        # eval shipped a sparse residue scatter instead of a dense
        # re-upload). The residue-rows gauge carries the watermark:
        # accumulated scatter rows atop parked masks are debt, and the
        # reclaim FOLDS the FeasMaskStore — parked entries drop, the
        # next eval re-parks a fresh combined mask (fold is safe
        # mid-wave: residue is applied per-eval on a copy, never
        # stored). spread_score_evals counts vectorized scoring-input
        # builds (ops/spread.py)
        from ..ops import spread as _spread_mod
        gov.register("feas.token_survivals",
                     lambda: _feas_mod.stats()["token_survivals"],
                     suspect=False)
        gov.register("feas.token_invalidations",
                     lambda: _feas_mod.stats()["token_invalidations"],
                     suspect=False)
        gov.register("feas.residue_rows",
                     lambda: self.store.table_cache.device.feas.debt(),
                     WatermarkPolicy(cfg.governor_feas_residue_high),
                     reclaim=lambda:
                     self.store.table_cache.device.feas.fold())
        gov.register("feas.spread_score_evals",
                     lambda: _spread_mod.stats()["spread_score_evals"],
                     suspect=False)

        # adaptive micro-batch gateway (server/worker.py, ISSUE 7):
        # live window, mean lanes per device dispatch, and the trigger
        # split — immediate (idle lane / unprofitable shape) vs
        # deadline (window expired while streaming). All monotone or
        # performance gauges, never drift suspects
        if self.gateway is not None:
            gw = self.gateway
            gov.register("gateway.window_us", gw.window_us, unit="us",
                         suspect=False)
            gov.register("gateway.batch_occupancy", gw.occupancy_mean,
                         unit="ratio", suspect=False)
            gov.register("gateway.immediate_dispatches",
                         lambda: gw.stats["immediate_dispatches"],
                         suspect=False)
            gov.register("gateway.deadline_dispatches",
                         lambda: gw.stats["deadline_dispatches"],
                         suspect=False)

        # batched write ingest (server/ingest.py, ISSUE 19): queue
        # depth carries the watermark whose reclaim HALVES the window
        # (a deep queue means the committer is saturated — waiting for
        # companions only adds latency; the drain trigger already
        # self-clocks batch formation). The shed/coalesced counters
        # are monotone, never drift suspects
        if self.ingest is not None:
            ing = self.ingest
            gov.register("ingest.queue_depth", ing.queue_depth,
                         WatermarkPolicy(cfg.governor_ingest_queue_high,
                                         pressure=True),
                         reclaim=ing.shrink_window)
            gov.register("ingest.queue_bytes", ing.queue_bytes,
                         suspect=False)
            gov.register("ingest.window_us", ing.window_us, unit="us",
                         suspect=False)
            gov.register("ingest.batch_size", ing.mean_batch_size,
                         suspect=False)
            gov.register("ingest.coalesced_writes",
                         lambda: ing.stats["coalesced_writes"],
                         suspect=False)
            gov.register("ingest.shed", lambda: ing.stats["shed"],
                         suspect=False)
            gov.register("ingest.write_p99_ms", ing.write_p99_ms,
                         unit="ms", suspect=False)

        # recompile visibility (analysis/sanitizer.py): distinct
        # compiled trace signatures across every kernel arm — a
        # recompile storm shows up in /v1/operator/governor as a
        # climbing gauge, not a mystery p99. suspect=False: monotone
        # by construction, it must not out-rank a real leak in drift
        # findings
        from ..analysis.sanitizer import traces as lint_traces
        gov.register("lint.recompiles", lint_traces.count,
                     suspect=False)

        # lock traffic (analysis/race.py, ISSUE 14): populated only
        # when NOMAD_TPU_RACE=1 armed the shims — zeros otherwise.
        # All monotone counters or bounded structures, never drift
        # suspects. The worst-holder exemplars ride the `locks` block
        # of /v1/operator/governor (extra_status below)
        from ..analysis import race as _race_mod
        gov.register("lock.tracked", _race_mod.monitor.tracked_locks,
                     suspect=False)
        gov.register("lock.order_edges", _race_mod.monitor.edge_count,
                     suspect=False)
        gov.register("lock.contended_acquires",
                     _race_mod.monitor.contended_total, suspect=False)
        gov.register("lock.hold_warnings",
                     _race_mod.monitor.hold_warns_total, suspect=False)
        gov.register("lock.findings",
                     _race_mod.monitor.unsuppressed_count,
                     suspect=False)
        gov.extra_status["locks"] = _race_mod.monitor.status_snapshot

        # flight-recorder visibility (ISSUE 9): ring occupancy and the
        # exemplar count in /v1/operator/governor. suspect=False: both
        # are bounded by construction
        from ..trace import tracer as _flight
        gov.register("trace.ring_traces", _flight.ring_len,
                     suspect=False)
        gov.register("trace.exemplars", _flight.exemplar_count,
                     suspect=False)

        # distributed scheduler plane (server/follower_sched.py, ISSUE
        # 16). Leader-side reads come from the lease table (remote
        # dequeue/demotion counters, leases outstanding — the bounded
        # in-flight remote set carries no watermark: the lease sweeper
        # IS its reclaim); the fence-wait p99 reads the FOLLOWER-side
        # reservoir through self.follower_sched, which attach_raft may
        # build after these lambdas are registered — hence the getattr
        leases = self.eval_leases
        gov.register("cluster_sched.remote_dequeues",
                     lambda: leases.stats["remote_dequeues"],
                     suspect=False)
        gov.register("cluster_sched.remote_demotions",
                     lambda: leases.stats["remote_demotions"],
                     suspect=False)
        gov.register("cluster_sched.leases_outstanding",
                     leases.outstanding)
        gov.register("cluster_sched.lease_expiries",
                     lambda: leases.stats["expired"], suspect=False)
        gov.register("cluster_sched.fence_wait_p99_ms",
                     lambda: (self.follower_sched.fence_wait_p99_ms()
                              if self.follower_sched is not None
                              else 0.0),
                     unit="ms", suspect=False)

        # admission control: the broker sheds fresh enqueues while any
        # pressure gauge is over
        self.eval_broker.pressure_fn = gov.backpressure

    def _auto_pin_exemplars(self, finding: dict) -> None:
        """Drift hook (ISSUE 9 satellite): a drift finding that names
        a suspect structure pins the flight recorder's CURRENT
        exemplar set — the worst span trees recorded while the drift
        was building are the capture an operator would have wanted
        `operator debug` to take automatically."""
        suspect = finding.get("suspect_structure")
        if not suspect:
            return
        reason = (f"drift:{finding.get('metric', '?')}"
                  f"->{suspect}")
        pinned = self.tracer.pin_exemplars(reason=reason)
        if pinned and self.governor is not None:
            self.governor.emit({"kind": "trace_pin",
                                "exemplars": pinned,
                                "suspect": suspect,
                                "metric": finding.get("metric")})

    def _register_persistence_gauges(self) -> None:
        """Snapshot cadence, off-thread serialization time, and skipped
        triggers (ISSUE 8 cold-start pipeline) — a snapshot that keeps
        getting skipped-in-flight means the store outgrew the writer
        and the WAL tail is ballooning. Registered separately from
        _register_governor_gauges because Persistence is constructed
        after the governor. All monotone/perf gauges, never drift
        suspects."""
        p = self.persistence
        gov = self.governor
        gov.register("persistence.snapshots",
                     lambda: p.stats["snapshots"], suspect=False)
        gov.register("persistence.snapshot_skipped_inflight",
                     lambda: p.stats["snapshot_skipped_inflight"],
                     suspect=False)
        gov.register("persistence.last_snapshot_s",
                     lambda: p.stats["last_snapshot_s"], unit="s",
                     suspect=False)
        gov.register("persistence.snapshot_errors",
                     lambda: p.stats["snapshot_errors"], suspect=False)

    def _emit_stats(self) -> None:
        """Periodic gauge emission (eval_broker.go:825 EmitStats,
        blocked_evals stats, worker counters)."""
        from ..utils import metrics
        while not getattr(self, "_shutdown", False):
            time.sleep(1.0)
            try:
                bs = self.eval_broker.stats
                metrics.set_gauge("nomad.broker.total_ready",
                                  bs.total_ready)
                metrics.set_gauge("nomad.broker.total_unacked",
                                  bs.total_unacked)
                metrics.set_gauge("nomad.broker.total_blocked",
                                  bs.total_blocked)
                metrics.set_gauge("nomad.broker.total_waiting",
                                  bs.total_waiting)
                metrics.set_gauge(
                    "nomad.blocked_evals.total_blocked",
                    len(getattr(self.blocked_evals, "_captured", {}))
                    + len(getattr(self.blocked_evals, "_escaped", {})))
                metrics.set_gauge(
                    "nomad.worker.total_processed",
                    sum(w.stats["processed"] for w in self.workers))
                metrics.set_gauge(
                    "nomad.worker.total_failed",
                    sum(w.stats["failed"] for w in self.workers))
                metrics.set_gauge("nomad.state.latest_index",
                                  self.store.latest_index())
            except Exception:       # pragma: no cover — best effort
                pass

    def revoke_leadership(self) -> None:
        """leader.go revokeLeadership:1038 — disable leader-only
        services; workers stay up, parked on the disabled broker."""
        self._leader = False
        rep = getattr(self, "_replication", None)
        if rep is not None:
            rep.stop()
            self._replication = None
        # remote-dequeue leases are leader state: the broker flush
        # below cancels every unack they covered, and the NEW leader
        # re-enqueues non-terminal evals from the store — stale leases
        # here would only nack evals we no longer own
        self.eval_leases.flush()
        self.eval_broker.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        self.plan_queue.set_enabled(False)
        self.periodic.set_enabled(False)
        self.deployments_watcher.set_enabled(False)
        self.node_drainer.set_enabled(False)
        self.event_sinks.set_enabled(False)
        with self._hb_lock:
            for t in self._heartbeat_timers.values():
                t.cancel()
            self._heartbeat_timers.clear()

    def scheduler_plane_status(self) -> dict:
        """Per-member scheduler-plane status for `nomad server
        members`, /v1/agent/members, and `operator debug` (ISSUE 16
        satellite): raft role + applied index per member, fence lag
        (the leader's last log index minus the member's applied index
        — exactly the gap a follower's snapshot fence would wait out),
        leased evals per follower from the leader's lease table, and
        this server's own plane counters."""
        raft = self.raft
        status = {
            "enabled": bool(self.config.follower_sched),
            "leases": self.eval_leases.snapshot_stats(),
            "follower": (self.follower_sched.snapshot_stats()
                         if self.follower_sched is not None else None),
            "members": [],
        }
        if raft is None:
            return status
        leased = self.eval_leases.by_follower()
        rows = {raft.self_addr: raft._handle_status({})}
        from ..rpc.client import RpcClient
        for addr in (self.store.server_members() or []):
            if addr in rows:
                continue
            try:
                c = RpcClient(addr, dial_timeout_s=0.5)
                try:
                    rows[addr] = c.call("Raft.Status", {}, timeout_s=1.0)
                finally:
                    c.close()
            except Exception:
                rows[addr] = None
        leader_last = 0
        for st in rows.values():
            if st and st.get("role") == "leader":
                leader_last = int(st.get("last_log_index") or 0)
        for addr in sorted(rows):
            st = rows[addr]
            if st is None:
                status["members"].append(
                    {"addr": addr, "role": "unreachable",
                     "applied_index": None, "fence_lag": None,
                     "leased_evals": leased.get(addr, 0)})
                continue
            applied = int(st.get("applied_index") or 0)
            status["members"].append(
                {"addr": addr, "role": st.get("role"),
                 "applied_index": applied,
                 "fence_lag": (max(0, leader_last - applied)
                               if leader_last else 0),
                 "leased_evals": leased.get(addr, 0)})
        return status

    def apply_replicated(self, index: int, msg_type: str,
                         enc_payload: dict) -> None:
        """Apply a COMMITTED log entry — leaders and followers share
        this path (raft.py _fsm_loop calls it in log order once the
        commit index covers the entry). Nested raft_apply calls from
        FSM side effects append their own log entries on the leader and
        are suppressed on followers — either way the effect arrives as
        its own committed entry, so replicas converge. Change events
        publish here, i.e. only for committed writes (the r3 advisor's
        follower-dirty-read finding)."""
        from .persistence import decode_payload
        payload = decode_payload(msg_type, enc_payload)
        tl = self._apply_tl
        with self._raft_l:
            if index <= self._raft_index:
                return              # duplicate delivery (batch overlap)
            tl.in_fsm_apply = True
            try:
                self._raft_index = index
                if self.persistence is not None:
                    self.persistence.record(index, msg_type, payload)
                fn = getattr(self, f"_apply_{msg_type}")
                fn(index, payload)
                self.time_table.witness(index)
                if self.persistence is not None:
                    self.persistence.maybe_snapshot(self.store)
            finally:
                tl.in_fsm_apply = False
            try:
                self.events.publish(events_from_apply(msg_type, payload,
                                                      index))
            except Exception:
                LOG.exception("event publish for %s", msg_type)

    def install_snapshot(self, data: dict,
                         base_index: Optional[int] = None) -> None:
        """Full-state reseed from the leader (fsm.go Restore:1374). The
        snapshot's raft base index is authoritative for the applied
        index: store.latest_index() undercounts whenever the tail holds
        entries that touch no table (election no-ops), and an applied
        index below the log base would let this node reissue
        already-used log indexes after winning an election (r3 advisor,
        high)."""
        with self._raft_l:
            self.store.restore(data)
            floor = self.store.latest_index() if base_index is None \
                else base_index
            self._raft_index = max(floor, self.store.latest_index())
            # snapshot-covered indexes were never published as events
            # on this node: raise the sink gap floor accordingly
            self.events.epoch_floor = max(self.events.epoch_floor,
                                          self._raft_index)
            if self.persistence is not None:
                self.persistence.snapshot(self.store)
        # adopt the snapshot's replicated membership
        if self.raft is not None:
            members = self.store.server_members()
            if members:
                self.raft.update_members(members)

    def shutdown(self) -> None:
        self._shutdown = True
        # scheduler plane FIRST (ISSUE 16 satellite: clean multi-server
        # teardown): follower dequeue loops and the lease sweeper talk
        # to REMOTE transports — detach them before any local subsystem
        # starts dying, so no loop is mid-RPC against a peer that this
        # process's teardown (or a concurrent peer's) already killed
        if self.follower_sched is not None:
            self.follower_sched.stop()
        self.eval_leases.stop()
        if self.persistence is not None:
            try:
                # a background snapshot writer racing teardown could
                # leave a half-written .tmp for the next boot to skip;
                # wait it out, then flush any fsync-pending WAL bytes
                self.persistence.wait_idle()
                self.persistence.commit_barrier()
                self.persistence.save_cost_model()
            except Exception:   # pragma: no cover — best effort
                LOG.exception("cost model save failed")
        if self.telemetry is not None:
            self.telemetry.stop()
        if self.governor is not None:
            self.governor.stop()
        # detach the flight recorder from this server's governor — but
        # only if a newer server hasn't already rebound the hooks (the
        # tracer is process-global; holding our closures past shutdown
        # would keep the dead governor graph reachable forever)
        fns = getattr(self, "_tracer_fns", None)
        if fns is not None:
            if self.tracer.threshold_fn is fns[0]:
                self.tracer.threshold_fn = None
            if self.tracer.gauge_fn is fns[1]:
                self.tracer.gauge_fn = None
        if getattr(self, "swim", None) is not None:
            self.swim.stop()
        if self.raft is not None:
            self.raft.stop()
        self._leader = False
        self.event_sinks.set_enabled(False)
        self.deployments_watcher.set_enabled(False)
        self.node_drainer.set_enabled(False)
        self.periodic.stop()
        for w in self.workers:
            w.stop()
        self.plan_applier.stop()
        if self.ingest is not None:
            self.ingest.stop()
        self.eval_broker.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        self.plan_queue.set_enabled(False)
        with self._hb_lock:
            for t in self._heartbeat_timers.values():
                t.cancel()
            self._heartbeat_timers.clear()

    def establish_leadership(self) -> None:
        """leader.go establishLeadership:222."""
        self.eval_broker.set_enabled(True)
        self.blocked_evals.set_enabled(True)
        self.plan_queue.set_enabled(True)
        self._leader = True
        self._restore_evals()
        # restored nodes need TTL timers or a dead node stays ready
        # forever (heartbeat.go initializeHeartbeatTimers)
        for node in self.store.nodes():
            if not node.terminal_status():
                self.reset_heartbeat_timer(node.id)
        # leader.go restorePeriodicDispatcher:222 — re-track periodic jobs
        self.periodic.set_enabled(True)
        for job in self.store.jobs():
            if job.is_periodic():
                self.periodic.add(job)
        self.deployments_watcher.set_enabled(True)
        self.node_drainer.set_enabled(True)
        # durable event sinks are a leader duty: workers resume from
        # each sink's raft-committed progress (event_sink_manager.go)
        self.event_sinks.set_enabled(True)
        # non-authoritative regions replicate ACL policies, global
        # tokens, and namespaces from the authoritative region
        # (leader.go:327-331)
        if self.config.authoritative_region and \
                self.config.authoritative_region != self.config.region:
            from .replication import ReplicationManager
            self._replication = ReplicationManager(self)
            self._replication.start()
        if self.raft is not None:
            # seed the replicated member set from static boot config on
            # first leadership (later joins/leaves mutate it), then run
            # the autopilot reaper. Threaded: establish_leadership runs
            # under the raft lock (same reason the election no-op is)
            def _seed():
                try:
                    if not self.store.server_members():
                        self.raft_apply(
                            "server_membership",
                            dict(members=[self.raft.self_addr]
                                 + list(self.raft.peers)))
                except Exception:
                    LOG.exception("membership seed failed")
            threading.Thread(target=_seed, daemon=True,
                             name="member-seed").start()
            # always spawned: the loop idles when the threshold is 0,
            # so `operator autopilot-set-config` can enable cleanup on
            # a live leader
            threading.Thread(target=self._autopilot_loop,
                             daemon=True, name="autopilot").start()

    def _reap_failed_evals(self) -> None:
        """Drain the broker's failed queue: mark the eval failed and
        create a delayed failed-follow-up so the work retries after the
        storm passes (leader.go reapFailedEvaluations:766)."""
        while self._leader:
            ev, token = self.eval_broker.dequeue([FAILED_QUEUE], timeout_s=0.5)
            if ev is None:
                continue
            if ev.type == JOB_TYPE_CORE:
                # core evals are in-memory only — drop, never persist;
                # the GC ticker will enqueue a fresh one next interval
                self.eval_broker.ack(ev.id, token)
                continue
            failed = ev.copy()
            failed.status = EVAL_STATUS_FAILED
            follow_up = ev.create_failed_follow_up_eval(
                self.config.failed_eval_unblock_delay_s)
            failed.next_eval = follow_up.id
            try:
                self.raft_apply("eval_update", dict(evals=[failed, follow_up]))
                self.eval_broker.ack(ev.id, token)
            except Exception:
                LOG.exception("failed-eval reap for %s", ev.id)

    def _schedule_periodic_gc(self) -> None:
        """leader.go schedulePeriodic:689 — enqueue `_core` GC evals on a
        ticker. These evals are in-memory only (never raft-applied)."""
        last = time.monotonic()
        while self._leader:
            time.sleep(min(self.config.gc_interval_s / 4.0, 0.5))
            if time.monotonic() - last < self.config.gc_interval_s:
                continue
            last = time.monotonic()
            for core_job in (CORE_JOB_EVAL_GC, CORE_JOB_JOB_GC,
                             CORE_JOB_NODE_GC, CORE_JOB_DEPLOYMENT_GC):
                self.eval_broker.enqueue(self._core_eval(core_job))

    def _core_eval(self, core_job: str) -> Evaluation:
        return Evaluation(
            priority=CORE_JOB_PRIORITY, type=JOB_TYPE_CORE,
            triggered_by=TRIGGER_SCHEDULED, job_id=core_job,
            status=EVAL_STATUS_PENDING,
            modify_index=self._raft_index)

    def force_gc(self) -> None:
        """`nomad system gc` (system_endpoint.go): a forced full GC pass."""
        self.eval_broker.enqueue(self._core_eval(CORE_JOB_FORCE_GC))

    def _restore_evals(self) -> None:
        """Re-enqueue non-terminal evals after leadership (leader.go:496)."""
        for ev in self.store.evals():
            if ev.should_enqueue():
                self.eval_broker.enqueue(ev)
            elif ev.should_block():
                self.blocked_evals.block(ev)

    # -- WAL replay (cold start; ISSUE 8 batched replay) ---------------
    # entry types whose replay batches through the store's bulk paths;
    # a batch flushes when the incoming entry shares a (namespace, job)
    # with one already pending, so the grouped transaction is EXACTLY
    # state-equivalent to sequential per-entry replay (the per-entry
    # side-effect loops only ever read/write their own job's rows)
    _REPLAY_BATCH_TYPES = ("eval_update", "alloc_client_update")

    def _replay_entries(self, entries, highest: int) -> int:
        """Replay the WAL tail into the FSM. Event publication is
        suppressed throughout (replay is not new history — the epoch
        floor is raised after), and runs of eval/alloc-update entries
        group into single store transactions
        (NOMAD_TPU_WAL_REPLAY_BATCH=0 forces the sequential path for
        bisection)."""
        import os as _os

        from ..utils import stages
        batch_on = _os.environ.get("NOMAD_TPU_WAL_REPLAY_BATCH", "1") \
            not in ("0", "off")
        t0 = time.perf_counter() if stages.enabled else 0.0
        pending: List = []          # one same-type run
        pending_jobs: set = set()
        applied = 0

        def job_keys(msg_type: str, p: dict) -> set:
            keys = {(e.namespace, e.job_id) for e in p.get("evals", [])}
            if msg_type == "alloc_client_update":
                keys |= {(a.namespace, a.job_id)
                         for a in p.get("allocs", [])}
            return keys

        def flush() -> None:
            if not pending:
                return
            if len(pending) == 1:
                self._replay_one(*pending[0])
            else:
                try:
                    if pending[0][1] == "eval_update":
                        self._replay_eval_updates(pending)
                    else:
                        self._replay_alloc_client_updates(pending)
                    for index, _mt, _p, ts in pending:
                        self._raft_index = max(self._raft_index, index)
                        if ts:
                            self.time_table.witness(index, ts)
                except Exception:
                    LOG.exception("batched WAL replay failed "
                                  "(%d %s entries)", len(pending),
                                  pending[0][1])
            pending.clear()
            pending_jobs.clear()

        for index, msg_type, payload, ts in entries:
            if index <= highest:
                continue
            applied += 1
            if batch_on and msg_type in self._REPLAY_BATCH_TYPES:
                keys = job_keys(msg_type, payload)
                if pending and (pending[0][1] != msg_type
                                or keys & pending_jobs):
                    flush()
                pending.append((index, msg_type, payload, ts))
                pending_jobs.update(keys)
                continue
            flush()
            self._replay_one(index, msg_type, payload, ts)
        flush()
        if stages.enabled:
            stages.add("wal_replay", time.perf_counter() - t0)
        return applied

    def _replay_one(self, index: int, msg_type: str, payload: dict,
                    ts: float) -> None:
        try:
            getattr(self, f"_apply_{msg_type}")(index, payload)
            self._raft_index = max(self._raft_index, index)
            if ts:
                self.time_table.witness(index, ts)
        except Exception:
            LOG.exception("WAL replay failed at %d/%s", index, msg_type)

    def _replay_eval_updates(self, pending: List) -> None:
        """N job-disjoint eval_update entries as ONE store transaction;
        the per-eval side effects run per entry exactly as
        _apply_eval_update would (broker/blocked are disabled during
        replay, so enqueue is a no-op; reconcile writes are real)."""
        self.store.upsert_evals_batch(
            [(index, p["evals"]) for index, _mt, p, _ts in pending])
        for index, _mt, p, _ts in pending:
            for ev in p["evals"]:
                self.enqueue_eval(ev)
                if ev.job_id and ev.type != JOB_TYPE_CORE:
                    self.store.reconcile_job_status(index, ev.namespace,
                                                    ev.job_id)

    def _replay_alloc_client_updates(self, pending: List) -> None:
        """N job-disjoint alloc_client_update entries: one batched
        store transaction for the alloc merges, then each entry's
        unblock/eval/status side effects in order (job-disjointness
        makes this exactly sequential-equivalent)."""
        self.store.update_allocs_from_client_batch(
            [(index, p["allocs"]) for index, _mt, p, _ts in pending])
        for index, _mt, p, _ts in pending:
            for stub in p["allocs"]:
                alloc = self.store.alloc_by_id(stub.id)
                if alloc is None or not alloc.client_terminal_status():
                    continue
                node = self.store.node_by_id(alloc.node_id)
                if node is not None:
                    self.blocked_evals.unblock(node.computed_class,
                                               index)
            for ev in p.get("evals", []):
                self.store.upsert_evals(index, [ev])
                self.enqueue_eval(ev)
            self._reconcile_job_statuses(index,
                                         {"allocs_placed": p["allocs"]})

    # -- raft apply ----------------------------------------------------
    def raft_apply(self, msg_type: str, payload: dict) -> int:
        """Serialized FSM apply (fsm.go Apply:210-300). Returns the
        index. Dev mode (no raft): record+apply+snapshot run inline
        under the raft lock so WAL order == apply order. Clustered: the
        leader appends the entry to the replication log and blocks
        until a majority holds it AND the local FSM has applied it
        (apply-at-commit — hashicorp/raft runs the FSM only up to the
        commit index, nomad/server.go:1214); non-leaders forward the
        write to the leader (rpc.go forward())."""
        index, waiter = self.raft_apply_async(msg_type, payload)
        if waiter is not None:
            waiter()
        return index

    def raft_apply_async(self, msg_type: str, payload: dict):
        """The non-blocking half of raft_apply: log append now, commit
        + FSM apply deferred. Returns (index, waiter) where waiter is
        None (nested/forwarded/no-raft: nothing to wait for at this
        frame) or a callable that blocks until the entry is
        majority-replicated in the term it was stamped with and applied
        locally, raising otherwise. The plan applier uses this to
        overlap plan N's replication with plan N+1's verification
        (plan_apply.go:44-70 pipelining). On a clustered leader NOTHING
        is applied at this point — a caller that needs to read its own
        write must invoke the waiter (raft_apply does); this is what
        closes the uncommitted-read window on a partitioned leader."""
        if self.raft is not None:
            if getattr(self._apply_tl, "in_fsm_apply", False):
                # nested FSM side effect during a committed apply: on
                # the leader it becomes its own log entry (applied when
                # it commits); on a follower the leader's equivalent
                # entry arrives via the log — suppress. Narrow window:
                # if leadership changes between an entry's commit and
                # its apply, NO node re-emits the nested write (every
                # replica applies it as a non-leader). The only such
                # write is the blocked-eval wake (_unblock_enqueue),
                # and the woken eval stays in state as blocked — the
                # new leader re-tracks it on establish_leadership, the
                # same stall-until-next-capacity-change the reference
                # accepts across failovers (blocked_evals.go:316).
                if self.raft.is_leader():
                    try:
                        idx, _term = self.raft.append_entry(
                            msg_type, payload)
                        return idx, None
                    except RuntimeError:
                        LOG.warning(
                            "nested %s write dropped: deposed during "
                            "FSM apply; state-derived recovery applies",
                            msg_type)
                        return self._raft_index, None
                return self._raft_index, None
            if not self.raft.is_leader():
                return self.raft.forward_apply(msg_type, payload), None
            # raises "not the leader" on a deposed leader — nothing
            # recorded, nothing applied
            index, term = self.raft.append_entry(msg_type, payload)
            raft = self.raft
            return index, lambda: raft.wait_for_applied(index, term)
        # dev / single-node: inline serialized apply. Change events fan
        # out inside the lock; WAL replay bypasses raft_apply so
        # restores don't replay the event history.
        with self._raft_l:
            index = self._raft_index + 1
            self._raft_index = index
            if self.persistence is not None:
                self.persistence.record(index, msg_type, payload)
            fn = getattr(self, f"_apply_{msg_type}")
            fn(index, payload)
            self.time_table.witness(index)
            if self.persistence is not None:
                # dev mode: the entry IS the commit unit, so the
                # group-fsync barrier sits right here
                self.persistence.commit_barrier()
                self.persistence.maybe_snapshot(self.store)
            try:
                self.events.publish(events_from_apply(
                    msg_type, payload, index))
            except Exception:
                LOG.exception("event publish for %s", msg_type)
        return index, None

    def _apply_noop(self, index: int, p: dict) -> None:
        """Leadership no-op (hashicorp/raft LogNoop): commits the new
        term without mutating state."""

    # -- FSM appliers --------------------------------------------------
    def _apply_job_register(self, index: int, p: dict) -> None:
        job: Job = p["job"]
        self.store.upsert_job(index, job)
        self.blocked_evals.untrack(job.namespace, job.id)
        self.store.reconcile_job_status(index, job.namespace, job.id)
        self.periodic.add(self.store.job_by_id(job.namespace, job.id) or job)
        for ev in p.get("evals", []):
            if not ev.job_modify_index:
                # ingest-embedded eval (ISSUE 19): the register and its
                # eval share one entry, so the fence is stamped at
                # apply time — deterministic on WAL replay too
                ev.job_modify_index = index
            self.store.upsert_evals(index, [ev])
            self.enqueue_eval(ev)

    def _apply_job_deregister(self, index: int, p: dict) -> None:
        namespace, job_id = p["namespace"], p["job_id"]
        if p.get("purge"):
            self.store.delete_job(index, namespace, job_id)
            self.periodic.remove(namespace, job_id)
        else:
            job = self.store.job_by_id(namespace, job_id)
            if job is not None:
                stopped = job.copy()
                stopped.stop = True
                self.store.upsert_job(index, stopped)
                self.store.reconcile_job_status(index, namespace, job_id)
                self.periodic.add(stopped)  # untracks a stopped periodic
        for ev in p.get("evals", []):
            self.store.upsert_evals(index, [ev])
            self.enqueue_eval(ev)

    def _apply_eval_update(self, index: int, p: dict) -> None:
        evals: List[Evaluation] = p["evals"]
        self.store.upsert_evals(index, evals)
        for ev in evals:
            self.enqueue_eval(ev)
            if ev.job_id and ev.type != JOB_TYPE_CORE:
                self.store.reconcile_job_status(index, ev.namespace, ev.job_id)

    def _apply_eval_delete(self, index: int, p: dict) -> None:
        self.store.delete_evals(index, p["eval_ids"], p.get("alloc_ids"))

    def _apply_node_register(self, index: int, p: dict) -> None:
        node: Node = p["node"]
        self.store.upsert_node(index, node)
        stored = self.store.node_by_id(node.id)
        if stored is not None and stored.ready():
            self.blocked_evals.unblock(stored.computed_class, index)

    def _apply_node_deregister(self, index: int, p: dict) -> None:
        self.store.delete_node(index, p["node_ids"])

    def _apply_node_status_update(self, index: int, p: dict) -> None:
        node_id, status = p["node_id"], p["status"]
        self.store.update_node_status(index, node_id, status, int(time.time()))
        node = self.store.node_by_id(node_id)
        if node is None:
            return
        if status == NODE_STATUS_READY:
            self.blocked_evals.unblock(node.computed_class, index)
        evals = p.get("evals", [])
        if evals:
            self.store.upsert_evals(index, evals)
            for ev in evals:
                self.enqueue_eval(ev)

    def _apply_node_eligibility_update(self, index: int, p: dict) -> None:
        self.store.update_node_eligibility(index, p["node_id"], p["eligibility"])
        node = self.store.node_by_id(p["node_id"])
        if node is not None and node.ready():
            self.blocked_evals.unblock(node.computed_class, index)

    def _apply_node_drain_update(self, index: int, p: dict) -> None:
        self.store.update_node_drain(index, p["node_id"], p["drain_strategy"],
                                     p.get("mark_eligible", False))

    def _apply_alloc_desired_transition(self, index: int, p: dict) -> None:
        self.store.update_alloc_desired_transitions(
            index, p["alloc_ids"], p["transition"], p.get("evals"))
        for ev in p.get("evals", []):
            self.enqueue_eval(ev)

    def _apply_alloc_client_update(self, index: int, p: dict) -> None:
        allocs: List[Allocation] = p["allocs"]
        self.store.update_allocs_from_client(index, allocs)
        # failed/stopped allocs free capacity -> unblock by node class
        for stub in allocs:
            alloc = self.store.alloc_by_id(stub.id)
            if alloc is None or not alloc.client_terminal_status():
                continue
            node = self.store.node_by_id(alloc.node_id)
            if node is not None:
                self.blocked_evals.unblock(node.computed_class, index)
        for ev in p.get("evals", []):
            self.store.upsert_evals(index, [ev])
            self.enqueue_eval(ev)
        self._reconcile_job_statuses(index, {"allocs_placed": allocs})

    def _apply_plan_results(self, index: int, p: dict) -> None:
        self.store.upsert_plan_results(
            index,
            allocs_stopped=p["allocs_stopped"],
            allocs_placed=p["allocs_placed"],
            allocs_preempted=p["allocs_preempted"],
            deployment=p.get("deployment"),
            deployment_updates=p.get("deployment_updates"),
            evals=p.get("evals"),
        )
        self._reconcile_job_statuses(index, p)

    def _apply_plan_group_results(self, index: int, p: dict) -> None:
        """One committed entry carrying a whole plan GROUP (the
        group-commit applier): N verified plans land as ONE state-store
        transaction — a single layer push instead of N — and publish
        their change events in one flush."""
        self.store.upsert_plan_group_results(index, p["groups"])
        for g in p["groups"]:
            self._reconcile_job_statuses(index, g)

    def _apply_ingest_batch(self, index: int, p: dict) -> None:
        """One committed entry carrying a whole ingest GROUP (ISSUE 19,
        server/ingest.py): coalesced registers / client alloc updates /
        desired transitions land in submission order, with each
        consecutive same-kind run collapsed to ONE store transaction
        (upsert_jobs_batch / update_allocs_from_client_batch). Per-kind
        side effects run per entry exactly as the singleton appliers
        would, so the final state is sequential-equivalent by
        construction."""
        entries = p["entries"]
        i = 0
        while i < len(entries):
            kind = entries[i]["kind"]
            j = i
            while j < len(entries) and entries[j]["kind"] == kind:
                j += 1
            run = entries[i:j]
            if kind == "job_register":
                self._ingest_apply_registers(index, run)
            elif kind == "alloc_client_update":
                self._ingest_apply_client_updates(index, run)
            else:
                for e in run:
                    self._apply_alloc_desired_transition(index, e)
            i = j

    def _ingest_apply_registers(self, index: int, run: List[dict]) -> None:
        # one store transaction for the run's jobs (in order, so a
        # same-job re-register in one batch still sees the version
        # bump), then the singleton applier's side-effect tail per job
        self.store.upsert_jobs_batch(index, [e["job"] for e in run])
        evals: List[Evaluation] = []
        for e in run:
            job: Job = e["job"]
            self.blocked_evals.untrack(job.namespace, job.id)
            self.store.reconcile_job_status(index, job.namespace, job.id)
            self.periodic.add(
                self.store.job_by_id(job.namespace, job.id) or job)
            for ev in e.get("evals", []):
                if not ev.job_modify_index:
                    ev.job_modify_index = index
                evals.append(ev)
        if evals:
            self.store.upsert_evals_batch([(index, evals)])
            for ev in evals:
                self.enqueue_eval(ev)

    def _ingest_apply_client_updates(self, index: int,
                                     run: List[dict]) -> None:
        # the r12 WAL-replay batch promoted to the live path: one store
        # transaction for the alloc merges, then each entry's
        # unblock/eval/status side effects in submission order
        self.store.update_allocs_from_client_batch(
            [(index, e["allocs"]) for e in run])
        for e in run:
            for stub in e["allocs"]:
                alloc = self.store.alloc_by_id(stub.id)
                if alloc is None or not alloc.client_terminal_status():
                    continue
                node = self.store.node_by_id(alloc.node_id)
                if node is not None:
                    self.blocked_evals.unblock(node.computed_class,
                                               index)
            for ev in e.get("evals", []):
                self.store.upsert_evals(index, [ev])
                self.enqueue_eval(ev)
            self._reconcile_job_statuses(index,
                                         {"allocs_placed": e["allocs"]})

    def _apply_scheduler_config(self, index: int, p: dict) -> None:
        self.store.set_scheduler_config(index, p["config"])

    # ACL appliers (fsm.go applyACL*; nomad/acl_endpoint.go)
    def _apply_acl_policy_upsert(self, index: int, p: dict) -> None:
        self.store.upsert_acl_policies(index, p["policies"])

    def _apply_acl_policy_delete(self, index: int, p: dict) -> None:
        self.store.delete_acl_policies(index, p["names"])

    def _apply_acl_token_upsert(self, index: int, p: dict) -> None:
        self.store.upsert_acl_tokens(index, p["tokens"])

    def _apply_acl_token_delete(self, index: int, p: dict) -> None:
        self.store.delete_acl_tokens(index, p["accessor_ids"])

    # namespace appliers (fsm.go applyNamespace*)
    def _apply_namespace_upsert(self, index: int, p: dict) -> None:
        self.store.upsert_namespaces(index, p["namespaces"])

    def _apply_namespace_delete(self, index: int, p: dict) -> None:
        self.store.delete_namespaces(index, p["names"])

    # service registry appliers (built-in catalog; the reference sends
    # these to Consul, command/agent/consul/service_client.go)
    def _apply_service_registration_upsert(self, index: int,
                                           p: dict) -> None:
        self.store.upsert_service_registrations(index, p["services"])

    def _apply_service_registration_delete(self, index: int,
                                           p: dict) -> None:
        self.store.delete_service_registrations(
            index, ids=p.get("ids"), alloc_ids=p.get("alloc_ids"))

    # CSI volume appliers (fsm.go applyCSIVolume*)
    def _apply_csi_volume_register(self, index: int, p: dict) -> None:
        self.store.upsert_csi_volumes(index, p["volumes"])

    def _apply_csi_volume_deregister(self, index: int, p: dict) -> None:
        self.store.delete_csi_volume(index, p["namespace"], p["volume_id"])

    def _apply_csi_volume_claim(self, index: int, p: dict) -> None:
        self.store.csi_volume_claim(index, p["namespace"], p["volume_id"],
                                    p["alloc_id"], p["node_id"],
                                    p["read_only"])

    def _apply_csi_volume_release(self, index: int, p: dict) -> None:
        self.store.csi_volume_release(index, p["namespace"],
                                      p["volume_id"], p["alloc_id"])

    def _apply_vault_accessor_upsert(self, index: int, p: dict) -> None:
        from ..server.vault import VaultAccessor
        from ..utils.codec import from_wire
        self.store.upsert_vault_accessors(
            index, [from_wire(VaultAccessor, w) for w in p["accessors"]])

    def _apply_vault_accessor_renew(self, index: int, p: dict) -> None:
        a = self.store.vault_accessor(p["accessor"])
        if a is not None:
            from dataclasses import replace
            self.store.upsert_vault_accessors(
                index, [replace(a, expire_time=p["expire_time"])])

    def _apply_vault_accessor_delete(self, index: int, p: dict) -> None:
        self.store.delete_vault_accessors(index, list(p["accessors"]))

    def _apply_periodic_launch(self, index: int, p: dict) -> None:
        self.store.upsert_periodic_launch(index, p["namespace"], p["job_id"],
                                          p["launch_time"])

    def _apply_deployment_delete(self, index: int, p: dict) -> None:
        self.store.delete_deployments(index, p["deployment_ids"])

    def _apply_deployment_status_update(self, index: int, p: dict) -> None:
        self.store.update_deployment_status(
            index, p["update"], p.get("job"), p.get("evals"))
        st = p.get("stability")
        if st:
            # same raft entry as the status change: success + stable marker
            # commit or replay together
            self.store.update_job_stability(
                index, st["namespace"], st["job_id"], st["version"],
                st["stable"])
        for ev in p.get("evals", []):
            self.enqueue_eval(ev)

    def _apply_deployment_promotion(self, index: int, p: dict) -> None:
        self.store.update_deployment_promotion(
            index, p["deployment_id"], p.get("groups"), p.get("evals"))
        for ev in p.get("evals", []):
            self.enqueue_eval(ev)

    def _apply_job_stability(self, index: int, p: dict) -> None:
        self.store.update_job_stability(
            index, p["namespace"], p["job_id"], p["version"], p["stable"])

    def _reconcile_job_statuses(self, index: int, p: dict) -> None:
        """Derive job status from alloc states (fsm setJobStatus analog)."""
        seen = set()
        for stub in (p.get("allocs_placed", []) + p.get("allocs_stopped", [])
                     + p.get("allocs_preempted", [])):
            a = self.store.alloc_by_id(stub.id) or stub
            key = (a.namespace, a.job_id)
            if key in seen or not key[1]:
                continue
            seen.add(key)
            self.store.reconcile_job_status(index, *key)

    # -- eval routing --------------------------------------------------
    def enqueue_eval(self, ev: Evaluation) -> None:
        if ev.should_enqueue():
            self.eval_broker.enqueue(ev)
        elif ev.should_block():
            self.blocked_evals.block(ev)

    def _unblock_enqueue(self, ev: Evaluation) -> None:
        """Blocked eval woken: back to pending + broker."""
        woke = ev.copy()
        woke.status = EVAL_STATUS_PENDING
        index = self.raft_apply("eval_update", dict(evals=[woke]))

    # -- north-bound API (the RPC endpoint surface) --------------------
    def register_job(self, job: Job,
                     triggered_by: str = TRIGGER_JOB_REGISTER,
                     enforce_index: bool = False,
                     job_modify_index: int = 0
                     ) -> Optional[Evaluation]:
        """Job.Register (nomad/job_endpoint.go:79): the admission
        pipeline — canonicalize, implied constraints, validate — then
        upsert and create an eval. Periodic and parameterized jobs
        get no eval — the dispatcher / Job.Dispatch creates child jobs
        which do (job_endpoint.go:236-247). With `enforce_index`, the
        register is a compare-and-set against the job's current modify
        index (`job run -check-index`; job_endpoint.go:175
        RegisterEnforceIndexErrPrefix): 0 means "must not exist"."""
        if enforce_index:
            # check-and-apply must be atomic w.r.t. sibling enforced
            # registrations (two HTTP threads both reading index 7 and
            # both winning would be the lost update CAS exists to stop)
            with self._register_l:
                current = self.store.job_by_id(job.namespace, job.id)
                cur_idx = current.job_modify_index \
                    if current is not None else 0
                if current is None and job_modify_index != 0:
                    raise ValueError(
                        "Enforcing job modify index "
                        f"{job_modify_index}: job does not exist")
                if current is not None and \
                        job_modify_index != cur_idx:
                    raise ValueError(
                        "Enforcing job modify index "
                        f"{job_modify_index}: job exists with "
                        f"conflicting job modify index: {cur_idx}")
                return self._register_job_validated(job, triggered_by)
        return self._register_job_validated(job, triggered_by)

    def _register_job_validated(self, job: Job,
                                triggered_by: str
                                ) -> Optional[Evaluation]:
        job.canonicalize()
        # multiregion fan-out (job_endpoint.go:328 multiregionRegister
        # — enterprise in the reference, implemented here over the
        # federation peers): an unpinned multiregion job localizes one
        # copy per region entry; copies are region-pinned so they never
        # re-fan when they arrive at the peer
        if job.multiregion is not None and \
                job.region in ("", "global"):
            return self._multiregion_register(job, triggered_by)
        self._validate_register(job)
        return self._commit_register(job, triggered_by)

    def _validate_register(self, job: Job) -> None:
        """Post-canonicalize admission checks for one register —
        namespace existence, connect/expose hooks, implied constraints,
        spec validation. Raises ValueError; runs in the SUBMITTER's
        thread so a bad job in a bulk batch fails only its own slot,
        before anything is parked on the gateway."""
        # the requested namespace must exist (job_endpoint.go Register:
        # "non-existent namespace"); "default" exists implicitly
        if self.store.namespace_by_name(job.namespace) is None:
            raise ValueError(
                f"job {job.id!r} is in nonexistent namespace "
                f"{job.namespace!r}")
        # connect + expose-check hooks (job_endpoint_hook_connect.go,
        # job_endpoint_hook_expose_check.go): inject sidecar/gateway
        # proxy tasks and check expose paths before implied
        # constraints and validation
        from .connect_hook import (connect_mutate, connect_validate,
                                   expose_check_mutate,
                                   expose_check_validate)
        connect_mutate(job, self.config.connect_sidecar_driver,
                       self.config.connect_sidecar_config)
        errs = expose_check_validate(job)
        if not errs:
            expose_check_mutate(job)
        self._implied_constraints(job)
        errs = errs + connect_validate(job) + job.validate()
        if errs:
            raise ValueError("; ".join(errs))

    def _commit_register(self, job: Job,
                         triggered_by: str) -> Optional[Evaluation]:
        """Land one fully validated register. Through the ingest
        gateway (ISSUE 19) the job and its eval ride ONE coalesced
        entry — the eval's job-modify fence is stamped at apply time so
        WAL replay stays deterministic; without a gateway the unchanged
        two-entry path runs."""
        ev = None
        if not (job.is_periodic() or job.is_parameterized()):
            ev = Evaluation(
                namespace=job.namespace, priority=job.priority,
                type=job.type, triggered_by=triggered_by, job_id=job.id,
                status=EVAL_STATUS_PENDING)
        if self.ingest is not None:
            index = self.ingest.submit(
                "job_register",
                dict(job=job, evals=[ev] if ev is not None else []))
            if ev is None:
                return None
            ev.job_modify_index = index
            ev.modify_index = index
            return ev
        index = self.raft_apply("job_register", dict(job=job, evals=[]))
        if ev is None:
            return None
        ev.job_modify_index = index
        ev.modify_index = index
        self.raft_apply("eval_update", dict(evals=[ev]))
        return ev

    def register_jobs_bulk(self, jobs: List[Job],
                           triggered_by: str = TRIGGER_JOB_REGISTER
                           ) -> List:
        """Array-body bulk register (ISSUE 19, `PUT /v1/jobs` with a
        list): validate each job in the caller's thread, park every
        admitted one on the gateway, then gather — one raft entry /
        store transaction for the whole admitted run. Returns one
        result PER INPUT in order: an Evaluation (or None for
        periodic/parameterized jobs) on success, the Exception
        otherwise — a validation failure fails ONLY its own slot, a
        batch-commit failure fails every parked slot."""
        if self.ingest is None:
            out = []
            for job in jobs:
                try:
                    out.append(self.register_job(job, triggered_by))
                except Exception as e:
                    out.append(e)
            return out
        slots = []              # (future | None, ev | result, err | None)
        for job in jobs:
            try:
                job.canonicalize()
                if job.multiregion is not None and \
                        job.region in ("", "global"):
                    # multiregion fans out over federation peers —
                    # inherently per-job, never coalesced
                    slots.append((None, self._multiregion_register(
                        job, triggered_by), None))
                    continue
                self._validate_register(job)
                ev = None
                if not (job.is_periodic() or job.is_parameterized()):
                    ev = Evaluation(
                        namespace=job.namespace, priority=job.priority,
                        type=job.type, triggered_by=triggered_by,
                        job_id=job.id, status=EVAL_STATUS_PENDING)
                fut = self.ingest.submit_async(
                    "job_register",
                    dict(job=job, evals=[ev] if ev is not None else []))
                slots.append((fut, ev, None))
            except Exception as e:
                slots.append((None, None, e))
        out = []
        for fut, ev, err in slots:
            if err is not None:
                out.append(err)
                continue
            if fut is None:
                out.append(ev)      # multiregion result, already final
                continue
            try:
                index = fut.result()
                if ev is not None:
                    ev.job_modify_index = index
                    ev.modify_index = index
                out.append(ev)
            except Exception as e:
                out.append(e)
        return out

    def deregister_job_global(self, namespace: str, job_id: str,
                              purge: bool = False):
        """Multiregion stop (job_endpoint_oss.go multiregionStop):
        fan the deregister to every region in the stored job's
        multiregion block, then stop locally."""
        job = self.store.job_by_id(namespace, job_id)
        failed = []
        if job is not None and job.multiregion is not None:
            for entry in job.multiregion.regions:
                if entry.name == self.config.region:
                    continue
                peer = self.config.region_peers.get(entry.name)
                if not peer:
                    failed.append(f"{entry.name} (no federation peer)")
                    continue
                req = urllib.request.Request(
                    f"http://{peer}/v1/job/{job_id}?region={entry.name}"
                    f"&purge={str(purge).lower()}"
                    f"&namespace={namespace}",
                    method="DELETE")
                if self.config.replication_token:
                    req.add_header("X-Nomad-Token",
                                   self.config.replication_token)
                try:
                    with urllib.request.urlopen(req, timeout=60) as r:
                        r.read()
                except Exception as e:
                    LOG.exception("multiregion stop in %s failed",
                                  entry.name)
                    failed.append(f"{entry.name} ({e})")
        ev = self.deregister_job(namespace, job_id, purge=purge)
        if failed:
            # the local stop stuck, but the operator must hear that
            # other regions did NOT stop
            raise RuntimeError(
                f"job stopped in {self.config.region!r} but deregister "
                f"failed in: {', '.join(failed)}")
        return ev

    def _multiregion_register(self, job: Job, triggered_by: str):
        """Localize one copy per multiregion region entry and land it
        in that region: the local region registers directly, remote
        regions get an HTTP push through their federation peer. Region
        entries override datacenters, fill zero group counts, and merge
        meta (the documented enterprise semantics). Cross-region
        deployment pacing (max_parallel/on_failure) is not enforced —
        regions roll independently."""
        import copy
        errs = job.validate()
        if errs:
            raise ValueError("; ".join(errs))
        mr = job.multiregion
        missing = [r.name for r in mr.regions
                   if r.name != self.config.region
                   and r.name not in self.config.region_peers]
        if missing:
            raise ValueError(
                f"no federation peer for multiregion regions {missing}")
        local_eval = None
        for entry in mr.regions:
            local = copy.deepcopy(job)
            local.region = entry.name
            if entry.datacenters:
                local.datacenters = list(entry.datacenters)
            if entry.meta:
                local.meta = {**local.meta, **entry.meta}
            if entry.count > 0:
                for tg in local.task_groups:
                    if tg.count == 0:
                        tg.count = entry.count
            if entry.name == self.config.region:
                local_eval = self.register_job(local, triggered_by)
            else:
                self._push_job_to_region(entry.name, local)
        return local_eval

    def _push_job_to_region(self, region: str, job: Job) -> None:
        import urllib.request
        from ..utils.codec import to_wire
        peer = self.config.region_peers[region]
        body = json.dumps({"Job": to_wire(job)}).encode()
        headers = {"Content-Type": "application/json"}
        if self.config.replication_token:
            headers["X-Nomad-Token"] = self.config.replication_token
        req = urllib.request.Request(
            f"http://{peer}/v1/jobs?region={region}", data=body,
            method="PUT", headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                resp.read()
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read()).get("error", str(e))
            except Exception:
                msg = str(e)
            raise ValueError(f"multiregion register in {region!r} "
                             f"failed: {msg}")
        except urllib.error.URLError as e:
            raise RuntimeError(f"multiregion register: no route to "
                               f"region {region!r}: {e.reason}")

    def evaluate_job(self, namespace: str, job_id: str) -> Evaluation:
        """Force a fresh evaluation of a job (job_endpoint.go
        Evaluate) — `nomad job eval`."""
        job = self.store.job_by_id(namespace, job_id)
        if job is None:
            raise KeyError(f"job {job_id} not found")
        ev = Evaluation(
            namespace=namespace, priority=job.priority, type=job.type,
            triggered_by=TRIGGER_JOB_REGISTER, job_id=job_id,
            status=EVAL_STATUS_PENDING)
        self.raft_apply("eval_update", dict(evals=[ev]))
        return ev

    def stop_alloc(self, alloc_id: str) -> Evaluation:
        """Stop one allocation and evaluate its job for a replacement
        (alloc_endpoint.go Stop: a desired transition plus an eval)."""
        from ..models.alloc import DesiredTransition
        alloc = self.store.alloc_by_id(alloc_id)
        if alloc is None:
            raise KeyError(f"alloc {alloc_id[:8]} not found")
        job = alloc.job or self.store.job_by_id(alloc.namespace,
                                                alloc.job_id)
        ev = Evaluation(
            namespace=alloc.namespace,
            priority=job.priority if job else 50,
            type=job.type if job else "service",
            triggered_by="alloc-stop", job_id=alloc.job_id,
            status=EVAL_STATUS_PENDING)
        payload = dict(alloc_ids=[alloc_id],
                       transition=DesiredTransition(migrate=True),
                       evals=[ev])
        if self.ingest is not None:
            self.ingest.submit("alloc_desired_transition", payload)
        else:
            self.raft_apply("alloc_desired_transition", payload)
        return ev

    def dispatch_job(self, namespace: str, job_id: str,
                     payload: bytes = b"",
                     meta: Optional[Dict[str, str]] = None) -> Evaluation:
        """Job.Dispatch (nomad/job_endpoint.go Dispatch): instantiate a
        parameterized job as a one-shot child with the given payload and
        meta. Child ID is `<parent>/dispatch-<unix>-<rand>`."""
        import os
        meta = dict(meta or {})
        parent = self.store.job_by_id(namespace, job_id)
        if parent is None:
            raise KeyError(f"job {job_id} not found")
        if not parent.is_parameterized():
            raise ValueError(f"job {job_id} is not parameterized")
        if parent.stopped():
            raise ValueError(f"job {job_id} is stopped")
        cfg = parent.parameterized_job
        if cfg.payload == "forbidden" and payload:
            raise ValueError("payload forbidden by the parameterized job")
        if cfg.payload == "required" and not payload:
            raise ValueError("payload required by the parameterized job")
        if len(payload) > 16 * 1024:
            raise ValueError("payload exceeds the 16KiB maximum")
        required = set(cfg.meta_required)
        allowed = required | set(cfg.meta_optional)
        missing = required - set(meta)
        if missing:
            raise ValueError(f"missing required meta keys: {sorted(missing)}")
        unexpected = set(meta) - allowed
        if unexpected:
            raise ValueError(f"unpermitted meta keys: {sorted(unexpected)}")

        child = parent.copy()
        child.id = (f"{parent.id}/dispatch-{int(time.time())}-"
                    f"{os.urandom(4).hex()}")
        child.parent_id = parent.id
        child.dispatched = True
        child.payload = payload
        child.meta = {**parent.meta, **meta}
        child.status = ""
        child.stable = False
        child.version = 0
        ev = self.register_job(child)
        assert ev is not None
        return ev

    def deregister_job(self, namespace: str, job_id: str,
                       purge: bool = False) -> Evaluation:
        job = self.store.job_by_id(namespace, job_id)
        ev = Evaluation(
            namespace=namespace,
            priority=job.priority if job else 50,
            type=job.type if job else JOB_TYPE_SERVICE,
            triggered_by=TRIGGER_JOB_DEREGISTER, job_id=job_id,
            status=EVAL_STATUS_PENDING)
        self.raft_apply("job_deregister",
                        dict(namespace=namespace, job_id=job_id, purge=purge,
                             evals=[ev]))
        return ev

    def plan_job(self, job: Job, diff: bool = True) -> dict:
        """Job.Plan (nomad/job_endpoint.go Plan:600): dry-run the
        scheduler against a copy of current state; nothing is committed.
        Returns the annotated plan, failed placements, and the job diff."""
        from ..models.diff import job_diff
        from ..scheduler.harness import Harness
        job = job.copy()
        job.canonicalize()
        errs = job.validate()
        if errs:
            raise ValueError("; ".join(errs))
        old_job = self.store.job_by_id(job.namespace, job.id)

        shadow = StateStore()
        shadow.restore(self.store.dump())
        h = Harness(shadow)
        index = self.store.latest_index() + 1
        shadow.upsert_job(index, job)
        ev = Evaluation(
            namespace=job.namespace, priority=job.priority, type=job.type,
            triggered_by=TRIGGER_JOB_REGISTER, job_id=job.id,
            status=EVAL_STATUS_PENDING, annotate_plan=True)
        ev.job_modify_index = index
        h.process(job.type if job.type in self.config.enabled_schedulers
                  else JOB_TYPE_SERVICE, ev)
        plan = h.plans[-1] if h.plans else None
        from ..utils.codec import to_wire
        annotations = (to_wire(plan.annotations)
                       if plan is not None and plan.annotations else None)
        final_eval = h.evals[-1] if h.evals else ev
        the_diff = None
        if diff:
            # the diff carries human-readable annotations (update
            # counts, forces-* markers — scheduler/annotate.go)
            from ..scheduler.annotate import annotate
            the_diff = annotate(
                job_diff(old_job, job),
                {"DesiredTGUpdates": annotations["desired_tg_updates"]}
                if annotations else None)
        return {
            "annotations": annotations,
            "failed_tg_allocs": {tg: to_wire(m) for tg, m in
                                 (final_eval.failed_tg_allocs or {}).items()},
            "diff": the_diff,
            "job_modify_index": old_job.job_modify_index if old_job else 0,
            "next_version": (old_job.version + 1
                             if old_job is not None
                             and old_job.specchanged(job) else
                             old_job.version if old_job else 0),
        }

    def scale_job(self, namespace: str, job_id: str, group: str,
                  count: Optional[int] = None, message: str = "",
                  error: bool = False) -> Optional[Evaluation]:
        """Job.Scale (nomad/job_endpoint.go Scale:969): adjust one task
        group's count within its scaling policy bounds; always records a
        scaling event (the autoscaler's audit trail)."""
        from ..models.evaluation import TRIGGER_JOB_SCALE
        job = self.store.job_by_id(namespace, job_id)
        if job is None:
            raise KeyError(f"job {job_id} not found")
        if job.stopped():
            raise ValueError(f"job {job_id} is stopped")
        job = job.copy()
        tg = job.lookup_task_group(group)
        if tg is None:
            raise KeyError(f"task group {group!r} not found in {job_id}")
        ev = None
        if count is not None and not error:
            if tg.scaling is not None:
                if count < tg.scaling.min:
                    raise ValueError(
                        f"count {count} below scaling policy minimum "
                        f"{tg.scaling.min}")
                if tg.scaling.max and count > tg.scaling.max:
                    raise ValueError(
                        f"count {count} above scaling policy maximum "
                        f"{tg.scaling.max}")
            prev = tg.count
            tg.count = count
            ev = self.register_job(job, triggered_by=TRIGGER_JOB_SCALE)
            message = message or f"scaled from {prev} to {count}"
        self.raft_apply("scaling_event", dict(
            namespace=namespace, job_id=job_id,
            event=dict(task_group=group, count=count, message=message,
                       error=error, eval_id=ev.id if ev else "",
                       time=int(time.time()))))
        return ev

    # -- dynamic membership (nomad/serf.go + nomad/autopilot.go) -------
    def _apply_server_membership(self, index: int, p: dict) -> None:
        members = list(p.get("members") or [])
        self.store.set_server_members(index, members)
        if self.raft is not None:
            self.raft.update_members(members)

    def join_member(self, addr: str) -> List[str]:
        """Add a server to the voter set (Server.Join; the joiner calls
        this through any member — writes forward to the leader).
        Returns the post-join member list. The read-modify-write of
        the full list is serialized per leader so concurrent joins
        cannot overwrite each other's membership."""
        if self.raft is None:
            raise RuntimeError("not a clustered server")
        with self._member_l:
            current = self.store.server_members() or \
                [self.raft.self_addr] + list(self.raft.peers)
            if addr not in current:
                self.raft_apply("server_membership",
                                dict(members=current + [addr]))
        return self.store.server_members()

    def leave_member(self, addr: str) -> List[str]:
        """Remove a server from the voter set (operator leave or
        autopilot dead-server cleanup)."""
        if self.raft is None:
            raise RuntimeError("not a clustered server")
        with self._member_l:
            current = self.store.server_members() or \
                [self.raft.self_addr] + list(self.raft.peers)
            if addr in current:
                self.raft_apply(
                    "server_membership",
                    dict(members=[m for m in current if m != addr]))
        return self.store.server_members()

    def join_cluster(self, via_addr: str) -> None:
        """Joiner side: ask an existing member to add us, then adopt
        the returned member list (the serf-join analog)."""
        if self.raft is None:
            raise RuntimeError("attach_raft first")
        from ..rpc.client import RpcClient
        c = RpcClient(via_addr, dial_timeout_s=3.0)
        try:
            res = c.call("Server.Join",
                         {"addr": self.raft.self_addr}, timeout_s=30.0)
        finally:
            c.close()
        members = list(res.get("members") or [])
        if members:
            self.raft.update_members(members)

    def handle_peer_failure_report(self, addr: str,
                                   reporter: str = "") -> bool:
        """A peer's SWIM verdict arrived (Server.ReportFailed). Leader
        only: verify the target is unreachable from HERE too (implicit
        refutation — a live server answers and the report is dropped),
        then remove it under the same quorum guard autopilot uses.
        Returns True when the member was removed."""
        raft = self.raft
        if raft is None or not raft.is_leader():
            from ..rpc.codec import RpcRefused
            raise RpcRefused("not the leader")
        if addr == raft.self_addr:
            return False
        members = self.store.server_members() or \
            [raft.self_addr] + list(raft.peers)
        if addr not in members:
            return False                # already gone
        if self.swim is not None and self.swim.probe_for_peer(addr):
            LOG.info("swim report for %s from %s refuted by leader "
                     "probe", addr, reporter)
            return False
        alive = len(members) - 1
        if alive * 2 <= len(members):
            LOG.warning("swim: not removing %s — quorum guard", addr)
            return False
        LOG.warning("swim: removing failed server %s (reported by %s)",
                    addr, reporter)
        self.leave_member(addr)
        return True

    def _autopilot_loop(self) -> None:
        """Leader-side dead-server cleanup (nomad/autopilot.go): a
        voter with no successful replication contact past the cleanup
        threshold is removed from the member set, as long as a quorum
        of the REMAINING members is intact."""
        import time as _time
        while self._leader and not getattr(self, "_shutdown", False):
            # re-read per tick: `operator autopilot-set-config` mutates
            # the threshold at runtime (0 disables without killing the
            # loop, so re-enabling works too)
            threshold = self.config.dead_server_cleanup_s
            _time.sleep(max(min(threshold / 4.0, 2.0), 0.5)
                        if threshold > 0 else 1.0)
            raft = self.raft
            if raft is None or not raft.is_leader() or threshold <= 0:
                continue
            now = _time.monotonic()
            peers = list(raft.peers)
            dead = [p for p in peers
                    if now - raft.last_contact.get(p, now) > threshold]
            if not dead:
                continue
            alive = len(peers) - len(dead) + 1
            for p in dead:
                # quorum guard: committing the removal itself needs a
                # majority of the CURRENT cluster — without it the
                # leave write just times out and blocks join/leave
                if alive * 2 <= len(peers) + 1:
                    break
                try:
                    LOG.warning("autopilot: removing dead server %s "
                                "(no contact for %.0fs)", p,
                                now - raft.last_contact.get(p, now))
                    self.leave_member(p)
                except Exception:
                    LOG.exception("autopilot cleanup of %s failed", p)

    # -- event sinks (nomad/stream/sink.go + event_sink_manager.go) ----
    def upsert_event_sink(self, sink) -> int:
        return self.raft_apply("event_sink_upsert", dict(sink=sink))

    def delete_event_sink(self, sink_id: str) -> int:
        return self.raft_apply("event_sink_delete", dict(sink_id=sink_id))

    def _apply_event_sink_upsert(self, index: int, p: dict) -> None:
        self.store.upsert_event_sink(index, p["sink"])

    def _apply_event_sink_delete(self, index: int, p: dict) -> None:
        self.store.delete_event_sink(index, p["sink_id"])

    def _apply_event_sink_progress(self, index: int, p: dict) -> None:
        self.store.update_event_sink_progress(index, p["sink_id"],
                                              int(p["index"]))

    def _apply_scaling_event(self, index: int, p: dict) -> None:
        self.store.add_scaling_event(index, p["namespace"], p["job_id"],
                                     p["event"])

    # -- deployment endpoints (nomad/deployment_endpoint.go) -----------
    def promote_deployment(self, deployment_id: str,
                           groups: Optional[List[str]] = None) -> Evaluation:
        return promote_deployment(self, deployment_id, groups)

    def fail_deployment(self, deployment_id: str,
                        **kw) -> Optional[Evaluation]:
        return fail_deployment(self, deployment_id, **kw)

    def pause_deployment(self, deployment_id: str, pause: bool) -> None:
        pause_deployment(self, deployment_id, pause)

    def revert_job(self, namespace: str, job_id: str,
                   version: int) -> Optional[Evaluation]:
        """Job.Revert (nomad/job_endpoint.go Revert): re-register an
        older version's spec as a new version."""
        target = self.store.job_by_id_and_version(namespace, job_id, version)
        if target is None:
            raise KeyError(f"job {job_id} version {version} not found")
        current = self.store.job_by_id(namespace, job_id)
        if current is not None and current.version == version:
            raise ValueError(
                f"job {job_id} is already at version {version}")
        rolled = target.copy()
        rolled.stable = False
        rolled.version = 0          # reassigned by upsert_job
        return self.register_job(rolled)

    # -- node drain (nomad/node_endpoint.go UpdateDrain) ---------------
    def update_node_drain(self, node_id: str, drain_strategy,
                          mark_eligible: bool = False) -> None:
        """Start or clear a drain. Stamps the force deadline from the
        spec's relative deadline (structs.go DrainStrategy.DeadlineTime)."""
        if drain_strategy is not None \
                and drain_strategy.drain_spec.deadline_s > 0 \
                and drain_strategy.force_deadline == 0:
            drain_strategy.force_deadline = (
                time.time() + drain_strategy.drain_spec.deadline_s)
        self.raft_apply("node_drain_update",
                        dict(node_id=node_id, drain_strategy=drain_strategy,
                             mark_eligible=mark_eligible))

    def drain_allocs(self, allocs, jobs) -> None:
        drain_allocs(self, allocs, jobs)

    def register_node(self, node: Node) -> None:
        node.canonicalize()
        if not node.computed_class:
            node.compute_class()
        self.raft_apply("node_register", dict(node=node))
        self.reset_heartbeat_timer(node.id)

    def update_node_status(self, node_id: str, status: str) -> None:
        evals = []
        if status == NODE_STATUS_DOWN:
            evals = self._node_evals(node_id)
        self.raft_apply("node_status_update",
                        dict(node_id=node_id, status=status, evals=evals))

    def update_alloc_status_from_client(self, allocs: List[Allocation]) -> None:
        """Node.UpdateAlloc: client pushes task states; failed allocs
        trigger alloc-failure evals (node_endpoint.go:1065)."""
        evals = self._client_update_evals(allocs)
        payload = dict(allocs=allocs, evals=evals)
        if self.ingest is not None:
            self.ingest.submit("alloc_client_update", payload)
        else:
            self.raft_apply("alloc_client_update", payload)
        self._revoke_terminal_accessors(allocs)

    def update_alloc_status_from_client_batch(
            self, groups: List[List[Allocation]]) -> None:
        """Node.UpdateAllocBatch (ISSUE 19): N clients' update pushes
        in one verb. Each group keeps its own gateway entry (its evals
        are derived from pre-batch state exactly as N concurrent
        Node.UpdateAlloc calls would be), but all of them park together
        and land as one coalesced raft entry / store transaction."""
        if self.ingest is None:
            for g in groups:
                self.update_alloc_status_from_client(g)
            return
        futures = []
        for g in groups:
            evals = self._client_update_evals(g)
            futures.append(self.ingest.submit_async(
                "alloc_client_update", dict(allocs=g, evals=evals)))
        err = None
        for f in futures:
            try:
                f.result()
            except Exception as e:
                err = e
        for g in groups:
            self._revoke_terminal_accessors(g)
        if err is not None:
            raise err

    def _client_update_evals(self, allocs: List[Allocation]
                             ) -> List[Evaluation]:
        evals = []
        seen = set()
        for stub in allocs:
            existing = self.store.alloc_by_id(stub.id)
            if existing is None:
                continue
            if stub.client_status == "failed" and (existing.namespace,
                                                   existing.job_id) not in seen:
                job = self.store.job_by_id(existing.namespace, existing.job_id)
                if job is not None and not job.stopped():
                    seen.add((existing.namespace, existing.job_id))
                    evals.append(Evaluation(
                        namespace=existing.namespace, priority=job.priority,
                        type=job.type, triggered_by="alloc-failure",
                        job_id=existing.job_id, status=EVAL_STATUS_PENDING))
        return evals

    def _revoke_terminal_accessors(self, allocs: List[Allocation]) -> None:
        # revoke vault leases of allocs the client just reported
        # terminal (node_endpoint.go UpdateAlloc -> revokeVaultAccessors);
        # the reaper pass also catches these within its tick
        terminal = {a.id for a in allocs
                    if a.client_status in ("complete", "failed", "lost")}
        if terminal:
            doomed = [va.accessor for aid in terminal
                      for va in self.store.vault_accessors_by_alloc(aid)]
            self.revoke_vault_accessors(doomed)

    def _node_evals(self, node_id: str) -> List[Evaluation]:
        """One eval per job with allocs on the node + each system job
        (node_endpoint.go createNodeEvals:1318)."""
        evals = []
        jobs = set()
        for alloc in self.store.allocs_by_node(node_id):
            key = (alloc.namespace, alloc.job_id)
            if key in jobs:
                continue
            jobs.add(key)
            job = alloc.job or self.store.job_by_id(*key)
            if job is None:
                continue
            evals.append(Evaluation(
                namespace=key[0], priority=job.priority, type=job.type,
                triggered_by=TRIGGER_NODE_UPDATE, job_id=key[1],
                node_id=node_id, status=EVAL_STATUS_PENDING))
        for job in self.store.jobs():
            if job.type == JOB_TYPE_SYSTEM and job.namespaced_id() not in jobs \
                    and not job.stopped():
                evals.append(Evaluation(
                    namespace=job.namespace, priority=job.priority,
                    type=job.type, triggered_by=TRIGGER_NODE_UPDATE,
                    job_id=job.id, node_id=node_id,
                    status=EVAL_STATUS_PENDING))
        return evals

    # -- ACL (nomad/acl_endpoint.go; acl/acl.go engine) ----------------
    def bootstrap_acl(self):
        """One-time management-token mint (acl_endpoint.go Bootstrap).
        Raises if the cluster already has tokens."""
        from ..acl import new_token
        if self.store.acl_tokens():
            raise PermissionError("ACL bootstrap already done")
        token = new_token(name="Bootstrap Token", type_="management",
                          global_=True)
        self.raft_apply("acl_token_upsert", dict(tokens=[token]))
        return token

    def upsert_acl_policies(self, policies) -> int:
        from ..acl import parse_policy_rules
        for p in policies:
            if not p.name:
                raise ValueError("policy name required")
            parse_policy_rules(p.rules)        # validate
        return self.raft_apply("acl_policy_upsert", dict(policies=policies))

    def delete_acl_policies(self, names) -> int:
        return self.raft_apply("acl_policy_delete", dict(names=names))

    def create_acl_token(self, name: str = "", type_: str = "client",
                         policies=None, global_: bool = False):
        from ..acl import new_token
        if type_ not in ("client", "management"):
            raise ValueError(f"invalid token type {type_!r}")
        if type_ == "client" and not policies:
            raise ValueError("client token requires policies")
        token = new_token(name=name, type_=type_, policies=policies,
                          global_=global_)
        self.raft_apply("acl_token_upsert", dict(tokens=[token]))
        return token

    def delete_acl_tokens(self, accessor_ids) -> int:
        return self.raft_apply("acl_token_delete",
                               dict(accessor_ids=accessor_ids))

    def resolve_token(self, secret_id):
        """secret -> compiled ACL (nomad/acl.go ResolveToken). With ACLs
        disabled everything is management; with no token the anonymous
        deny-all ACL applies; unknown secrets are rejected."""
        from ..acl import ACL_MANAGEMENT, compile_acl
        from ..acl.acl import ACL_DENY_ALL
        if not self.config.acl_enabled:
            return ACL_MANAGEMENT
        if not secret_id:
            return ACL_DENY_ALL
        token = self.store.acl_token_by_secret(secret_id)
        if token is None:
            raise PermissionError("ACL token not found")
        if token.type == "management":
            return ACL_MANAGEMENT
        key = (tuple(sorted(token.policies)),
               self.store._root.indexes.get("acl_policies") or 0)
        cached = self._acl_cache.get(key)
        if cached is not None:
            return cached
        policies = [p for name in token.policies
                    if (p := self.store.acl_policy(name)) is not None]
        acl = compile_acl(policies)
        if len(self._acl_cache) > 256:
            self._acl_cache.clear()
        self._acl_cache[key] = acl
        return acl

    @staticmethod
    def _implied_constraints(job: Job) -> None:
        """jobImpliedConstraints (job_endpoint_hooks.go:114): auto-add
        group constraints implied by feature use — vault stanzas need a
        vault-capable node, signal-based change modes need nodes
        advertising those signals."""
        from ..models import Constraint
        for tg in job.task_groups:
            wants_vault = any(t.vault is not None for t in tg.tasks)
            signals = set()
            for t in tg.tasks:
                if t.kill_signal:
                    signals.add(t.kill_signal)
                if t.vault is not None and t.vault.change_signal:
                    signals.add(t.vault.change_signal)
                for tmpl in t.templates:
                    if tmpl.change_signal:
                        signals.add(tmpl.change_signal)
            have = {(c.ltarget, c.operand) for c in tg.constraints}
            if wants_vault and \
                    ("${attr.vault.version}", "is_set") not in have:
                tg.constraints.append(Constraint(
                    ltarget="${attr.vault.version}", rtarget="",
                    operand="is_set"))
            if signals and ("${attr.os.signals}",
                            "set_contains") not in have:
                tg.constraints.append(Constraint(
                    ltarget="${attr.os.signals}",
                    rtarget=",".join(sorted(signals)),
                    operand="set_contains"))

    # -- namespaces (nomad/namespace_endpoint.go) ----------------------
    def upsert_namespaces(self, namespaces: list) -> int:
        errs = []
        for ns in namespaces:
            errs.extend(ns.validate())
        if errs:
            raise ValueError("; ".join(errs))
        return self.raft_apply("namespace_upsert",
                               dict(namespaces=list(namespaces)))

    def delete_namespaces(self, names: list) -> int:
        """DeleteNamespaces:66 — "default" is undeletable and occupied
        namespaces (non-terminal jobs) refuse deletion."""
        from ..models.namespace import DEFAULT_NAMESPACE
        for name in names:
            if name == DEFAULT_NAMESPACE:
                raise ValueError("default namespace can not be deleted")
            if self.store.namespace_by_name(name) is None:
                raise KeyError(f"namespace {name} not found")
            occupied = [j.id for j in self.store.jobs()
                        if j.namespace == name
                        and j.status != "dead"]
            if occupied:
                raise ValueError(
                    f"namespace {name!r} has non-terminal jobs: "
                    f"{sorted(occupied)[:5]}")
        return self.raft_apply("namespace_delete", dict(names=names))

    # -- service registry (built-in catalog) ---------------------------
    def update_service_registrations(self, upserts=None,
                                     delete_alloc_ids=None,
                                     delete_ids=None) -> int:
        """Client-driven catalog sync: register live services, drop the
        rows of stopped allocs (the reference's Consul sync loop,
        command/agent/consul/service_client.go sync)."""
        index = 0
        if upserts:
            index = self.raft_apply("service_registration_upsert",
                                    dict(services=list(upserts)))
        if delete_alloc_ids or delete_ids:
            index = self.raft_apply(
                "service_registration_delete",
                dict(ids=list(delete_ids or []),
                     alloc_ids=list(delete_alloc_ids or [])))
        return index

    def list_services(self, namespace: str = "default") -> list:
        """Per-service summary (nomad service list analog): name, tags,
        live instance count."""
        summary: Dict[str, dict] = {}
        for s in self.store.service_registrations(namespace):
            row = summary.setdefault(
                s.service_name,
                {"ServiceName": s.service_name, "Namespace": s.namespace,
                 "Tags": set(), "Instances": 0})
            row["Tags"].update(s.tags)
            row["Instances"] += 1
        out = []
        for name in sorted(summary):
            row = summary[name]
            row["Tags"] = sorted(row["Tags"])
            out.append(row)
        return out

    def get_service(self, namespace: str, name: str) -> list:
        return self.store.service_by_name(namespace, name)

    # -- CSI volumes (nomad/csi_endpoint.go; volumewatcher/) -----------
    def register_csi_volume(self, volume) -> int:
        if not volume.id or not volume.plugin_id:
            raise ValueError("volume requires id and plugin_id")
        return self.raft_apply("csi_volume_register",
                               dict(volumes=[volume]))

    def deregister_csi_volume(self, namespace: str, volume_id: str,
                              force: bool = False) -> int:
        v = self.store.csi_volume(namespace, volume_id)
        if v is None:
            raise KeyError(f"volume {volume_id} not found")
        if not force and (v.read_allocs or v.write_allocs):
            raise ValueError(
                f"volume {volume_id} has active claims (use force)")
        return self.raft_apply("csi_volume_deregister",
                               dict(namespace=namespace,
                                    volume_id=volume_id))

    def _watch_volumes(self) -> None:
        """Volume watcher (nomad/volumewatcher): release claims held by
        terminal allocations so volumes become schedulable again."""
        while not getattr(self, "_shutdown", False):
            time.sleep(1.0)
            if not self._leader:
                continue
            try:
                for v in self.store.csi_volumes():
                    for aid in (list(v.read_allocs)
                                + list(v.write_allocs)):
                        alloc = self.store.alloc_by_id(aid)
                        if alloc is None or alloc.terminal_status():
                            self.raft_apply(
                                "csi_volume_release",
                                dict(namespace=v.namespace,
                                     volume_id=v.id, alloc_id=aid))
            except Exception:     # pragma: no cover — best effort
                LOG.exception("volume watcher pass failed")
            try:
                self._reap_vault_accessors()
            except Exception:     # pragma: no cover — best effort
                LOG.exception("vault accessor reap failed")

    # -- Vault integration (nomad/vault.go:176 vaultClient) ------------
    def derive_vault_token(self, alloc_id: str, tasks) -> Dict[str, dict]:
        """Token derivation for tasks with a vault stanza
        (node_endpoint.go DeriveVaultToken + vault.go CreateToken).
        The embedded authority mints a TTL'd token + accessor per task
        and tracks the lease in the replicated store, so revocation and
        renewal survive leader failover (see server/vault.py). Returns
        {task: {token, accessor, ttl_s}}."""
        alloc = self.store.alloc_by_id(alloc_id)
        if alloc is None:
            raise KeyError(f"alloc {alloc_id} not found")
        if alloc.terminal_status():
            raise ValueError(f"alloc {alloc_id} is terminal")
        from ..server.vault import VaultAccessor
        from ..utils.codec import to_wire
        from ..utils.ids import generate_uuid
        tg = alloc.job.lookup_task_group(alloc.task_group) \
            if alloc.job else None
        policies: Dict[str, list] = {}
        if tg is not None:
            for t in tg.tasks:
                if t.vault is not None:
                    policies[t.name] = list(t.vault.policies)
        now = time.time()
        ttl = self.config.vault_token_ttl_s
        accessors, out = [], {}
        # node_endpoint.go DeriveVaultToken: reject tasks that don't
        # exist in the alloc's group or carry no vault stanza — a
        # client must not be able to mint tokens for arbitrary names
        unknown = [t for t in tasks if t not in policies]
        if unknown:
            raise ValueError(
                f"tasks {unknown} do not exist in alloc {alloc_id} "
                "or have no vault stanza")
        for task in tasks:
            tok = f"s.{generate_uuid()[:24]}"
            acc = generate_uuid()
            accessors.append(VaultAccessor(
                accessor=acc, token=tok, alloc_id=alloc_id, task=task,
                node_id=alloc.node_id, policies=policies.get(task, []),
                ttl_s=ttl, create_time=now, expire_time=now + ttl))
            out[task] = {"token": tok, "accessor": acc, "ttl_s": ttl}
        self.raft_apply("vault_accessor_upsert",
                        dict(accessors=[to_wire(a) for a in accessors]))
        return out

    def renew_vault_token(self, accessor: str, token: str) -> float:
        """Extend a lease (vault.go RenewToken / client-side renewal
        loop target). Raises on unknown/revoked/expired leases — the
        client must re-derive then."""
        a = self.store.vault_accessor(accessor)
        if a is None or a.token != token:
            raise KeyError("unknown vault accessor")
        now = time.time()
        if a.expired(now):
            # reap lazily; the renewal failure tells the client to
            # re-derive (vaultclient.go renewal error path)
            self.raft_apply("vault_accessor_delete",
                            dict(accessors=[accessor]))
            raise ValueError("vault token lease expired")
        self.raft_apply("vault_accessor_renew",
                        dict(accessor=accessor,
                             expire_time=now + a.ttl_s))
        return a.ttl_s

    def revoke_vault_accessors(self, accessors: List[str]) -> None:
        """vault.go RevokeTokens: the embedded backend simply drops the
        lease rows — a dropped row IS an invalid token here."""
        if accessors:
            from ..utils import metrics
            metrics.incr_counter("nomad.vault.revoked", len(accessors))
            self.raft_apply("vault_accessor_delete",
                            dict(accessors=list(accessors)))

    def lookup_vault_token(self, token: str) -> bool:
        """Is this token currently valid? (vault TokenLookup analog,
        used by tests and operator introspection)."""
        a = self.store.vault_accessor_by_token(token)
        return a is not None and not a.expired()

    def _reap_vault_accessors(self) -> None:
        """Leader-side revocation daemon (vault.go revokeDaemon +
        nomad/node_endpoint.go revoking accessors of terminal allocs):
        drop leases whose alloc is gone/terminal or whose TTL lapsed
        without renewal."""
        now = time.time()
        doomed = []
        for a in self.store.vault_accessors():
            alloc = self.store.alloc_by_id(a.alloc_id)
            if alloc is None or alloc.terminal_status() or a.expired(now):
                doomed.append(a.accessor)
        self.revoke_vault_accessors(doomed)

    # -- heartbeats (nomad/heartbeat.go) -------------------------------
    def reset_heartbeat_timer(self, node_id: str) -> None:
        if self.raft is not None and not self._leader:
            return              # TTL timers are leader-only (heartbeat.go)
        with self._hb_lock:
            existing = self._heartbeat_timers.pop(node_id, None)
            if existing is not None:
                existing.cancel()
            t = threading.Timer(self.config.heartbeat_ttl_s,
                                self._invalidate_heartbeat, args=(node_id,))
            t.daemon = True
            self._heartbeat_timers[node_id] = t
            t.start()

    def _invalidate_heartbeat(self, node_id: str) -> None:
        node = self.store.node_by_id(node_id)
        if node is None or node.status == NODE_STATUS_DOWN:
            return
        LOG.warning("node %s missed heartbeat, marking down", node_id[:8])
        self.update_node_status(node_id, NODE_STATUS_DOWN)

    def heartbeat(self, node_id: str,
                  stats: Optional[dict] = None) -> float:
        """Client TTL renewal; returns the TTL. `stats` is the compact
        host-stats summary the client sampler attaches (ISSUE 13) —
        stashed per node for cluster_stats() to fold; O(1) per beat,
        the rollup itself runs at telemetry cadence, not here."""
        node = self.store.node_by_id(node_id)
        if node is None:
            raise KeyError(f"node {node_id} not registered")
        from ..chaos import faults as chaos_faults
        if chaos_faults.ACTIVE and \
                chaos_faults.fire("server.heartbeat", node_id=node_id):
            # chaos hook (ISSUE 15): the beat is dropped in transit —
            # the client believes it renewed, but the TTL timer keeps
            # running toward node-down and the stale-stats clock ages
            # the last payload toward `stale_heartbeats`
            return self.config.heartbeat_ttl_s
        if stats:
            with self._node_stats_l:
                self._node_stats[node_id] = {
                    **stats, "received_at": time.time()}
        if node.status != NODE_STATUS_READY:
            self.update_node_status(node_id, NODE_STATUS_READY)
        self.reset_heartbeat_timer(node_id)
        return self.config.heartbeat_ttl_s

    # -- cluster rollup (ISSUE 13) -------------------------------------
    def _telemetry_extra(self) -> Dict[str, float]:
        """The telemetry collector's extra_fn: device-mirror residency
        plus the cluster.* family, so fleet economics land in the
        retained ring every sample."""
        out: Dict[str, float] = {
            "device.mirror_bytes":
            self.store.table_cache.device_mirror_bytes()}
        for k, v in self.cluster_stats().items():
            out[f"cluster.{k}"] = v
        return out

    def cluster_stats(self) -> Dict[str, float]:
        """Fold per-node heartbeat host-stats into fleet economics:
        nodes up/down, capacity vs ALLOCATED (bin-packed, from the
        resident columnar node table) vs actually USED (host truth,
        from the heartbeat payloads), per-node utilization p50/p99,
        stale-heartbeat count. Pure host reads — O(nodes) numpy sums;
        also mirrors the family into the metrics registry so
        /v1/metrics?format=prometheus exposes nomad.cluster.*."""
        snap = self.store.snapshot()
        nodes = snap.nodes()
        now = time.time()
        with self._node_stats_l:
            # prune payloads for nodes the store no longer knows
            known = {n.id for n in nodes}
            for nid in list(self._node_stats):
                if nid not in known:
                    del self._node_stats[nid]
            stats = dict(self._node_stats)
        out: Dict[str, float] = {
            "nodes_total": float(len(nodes)),
            "nodes_ready": float(sum(1 for n in nodes if n.ready())),
            "nodes_down": float(sum(
                1 for n in nodes if n.status == NODE_STATUS_DOWN)),
            "nodes_reporting": 0.0,
            "stale_heartbeats": 0.0,
        }
        cap_cpu = cap_mem = 0.0
        for n in nodes:
            res = n.comparable_resources()
            cap_cpu += res.cpu_shares
            cap_mem += res.memory_mb
        out["fleet_cpu_capacity_mhz"] = cap_cpu
        out["fleet_mem_capacity_mb"] = cap_mem
        # allocated: the resident node table's live-alloc usage sums
        # (delta-maintained — no per-sample alloc scan). build=False:
        # a rollup must never trigger a cold table build; before the
        # first eval the allocated half reads 0 and catches up with
        # the first scheduled table
        alloc_cpu = alloc_mem = 0.0
        table = snap.node_table(build=False)
        if table is not None and table.n > 0:
            alloc_cpu = float(table.base_used[:, 0].sum())
            alloc_mem = float(table.base_used[:, 1].sum())
        out["fleet_cpu_allocated_mhz"] = alloc_cpu
        out["fleet_mem_allocated_mb"] = alloc_mem
        out["fleet_cpu_allocated_ratio"] = \
            round(alloc_cpu / cap_cpu, 4) if cap_cpu > 0 else 0.0
        out["fleet_mem_allocated_ratio"] = \
            round(alloc_mem / cap_mem, 4) if cap_mem > 0 else 0.0
        # used: host truth from the heartbeat payloads — a node's
        # host-level utilization FRACTION (cpu percent, mem
        # used/total) scaled by its configured capacity, so both used
        # sums stay commensurate with the capacity denominator and a
        # host busier than its schedulable share can't push a fleet
        # ratio past 1.0. Stale payloads drop out of the used sums
        # (their capacity still counts: unreported usage is unknown,
        # not 0)
        used_cpu = used_mem = 0.0
        cpu_pcts: List[float] = []
        mem_ratios: List[float] = []
        stale_after = self.config.stats_stale_after_s
        by_id = {n.id: n for n in nodes}
        for nid, st in stats.items():
            if now - st.get("received_at", 0.0) > stale_after:
                out["stale_heartbeats"] += 1.0
                continue
            node = by_id.get(nid)
            if node is None:
                continue
            out["nodes_reporting"] += 1.0
            res = node.comparable_resources()
            pct = float(st.get("cpu_pct", 0.0))
            used_cpu += pct / 100.0 * res.cpu_shares
            cpu_pcts.append(pct)
            total = float(st.get("mem_total_mb", 0.0))
            if total > 0:
                ratio = min(
                    float(st.get("mem_used_mb", 0.0)) / total, 1.0)
                used_mem += ratio * res.memory_mb
                mem_ratios.append(ratio)
        out["fleet_cpu_used_mhz"] = round(used_cpu, 1)
        out["fleet_mem_used_mb"] = round(used_mem, 1)
        out["fleet_cpu_used_ratio"] = \
            round(used_cpu / cap_cpu, 4) if cap_cpu > 0 else 0.0
        out["fleet_mem_used_ratio"] = \
            round(used_mem / cap_mem, 4) if cap_mem > 0 else 0.0
        if cpu_pcts:
            arr = np.asarray(cpu_pcts)
            out["node_cpu_pct_p50"] = round(
                float(np.percentile(arr, 50)), 3)
            out["node_cpu_pct_p99"] = round(
                float(np.percentile(arr, 99)), 3)
        if mem_ratios:
            arr = np.asarray(mem_ratios)
            out["node_mem_ratio_p50"] = round(
                float(np.percentile(arr, 50)), 4)
            out["node_mem_ratio_p99"] = round(
                float(np.percentile(arr, 99)), 4)
        for k in ("nodes_total", "nodes_ready", "nodes_down",
                  "nodes_reporting", "stale_heartbeats",
                  "fleet_cpu_used_ratio", "fleet_mem_used_ratio",
                  "fleet_cpu_allocated_ratio",
                  "fleet_mem_allocated_ratio"):
            metrics.set_gauge(f"nomad.cluster.{k}", out[k])
        return out
