"""Multi-server consensus: leader election + log replication + snapshot
install over the wire RPC layer.

Reference shape: nomad/server.go setupRaft:1214 (hashicorp/raft),
nomad/leader.go monitorLeadership:54 (establish/revoke hooks),
nomad/fsm.go Snapshot/Restore:1360-1374, rpc.go forward() (writes go to
the leader). SURVEY §7.2 step 7 blesses a "single-leader Raft-lite":

  - terms + randomized election timeouts + majority votes with the
    log-up-to-date check (Raft §5.2/§5.4.1)
  - the leader assigns log indexes and applies entries to its FSM
    immediately (the pre-existing single-node raft_apply semantics are
    preserved bit-for-bit, including nested applies); followers receive
    entries in order over AppendEntries and apply them with nested
    side-effect applies suppressed (the leader's equivalents arrive as
    their own entries)
  - **commit means commit**: the leader acks a write only once a
    majority of the cluster holds the entry (match-index quorum over
    per-peer replication threads, Raft §5.3/§5.4), with the
    current-term commit rule (§5.4.2, figure 8) enforced via a no-op
    entry appended on election (the hashicorp/raft noop). A leader that
    cannot reach a majority times out the ack instead of claiming
    durability
  - replication runs in one dedicated thread per peer (hashicorp/raft
    replication.go shape) so a dead peer or an in-flight snapshot
    install can never starve heartbeats to healthy followers
  - a follower whose applied state diverges from the new leader's log
    (e.g. a deposed leader with an unreplicated applied tail) cannot
    truncate applied state; it is reseeded with a full snapshot install
    (store.dump()/restore()), the FSM-snapshot analog
  - membership is static configuration (no serf/autopilot)

Write forwarding: a non-leader server forwards (msg_type, payload)
through Raft.Forward; the client-facing RPC layer additionally forwards
whole write RPCs to the leader (rpc.go forward()).
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from .persistence import decode_payload, encode_payload

LOG = logging.getLogger("nomad_tpu.raft")

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"

HEARTBEAT_S = 0.1
ELECTION_MIN_S = 0.5
ELECTION_MAX_S = 1.0
MAX_BATCH = 256


class RaftNode:
    def __init__(self, server, self_addr: str, peers: List[str],
                 data_dir: str = ""):
        self.server = server
        self.self_addr = self_addr
        self.peers = [p for p in peers if p != self_addr]
        self.cluster_size = len(self.peers) + 1
        self.data_dir = data_dir

        self._lock = threading.RLock()
        self.role = FOLLOWER
        self.term = 0
        self.voted_for: Optional[str] = None
        self.leader_addr: Optional[str] = None
        # entries AFTER the compaction base: (index, term, type, enc)
        self.log: List[Tuple[int, int, str, dict]] = []
        self.base_index = server._raft_index
        self.base_term = 0
        self.needs_snapshot = False

        self._last_heartbeat = time.monotonic()
        self._election_deadline = self._new_deadline()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # per-peer replication state (leader)
        self._next_index: Dict[str, int] = {}
        self._match_index: Dict[str, int] = {}
        self._clients: Dict[str, object] = {}
        # quorum commit tracking: an entry is committed once a majority
        # of match indexes cover it and it belongs to the current term
        self.commit_index = self.base_index
        self._commit_cv = threading.Condition(self._lock)
        self._repl_gen = 0            # invalidates stale repl threads
        self._repl_events: Dict[str, threading.Event] = {}
        self._load_vote_state()

    # -- persistence of (term, votedFor) — Raft §5.1 -------------------
    def _vote_path(self) -> str:
        return os.path.join(self.data_dir, "raft_vote.json") \
            if self.data_dir else ""

    def _load_vote_state(self) -> None:
        path = self._vote_path()
        if not path or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                d = json.load(f)
            self.term = int(d.get("term", 0))
            self.voted_for = d.get("voted_for")
        except (OSError, json.JSONDecodeError):
            pass

    def _save_vote_state(self) -> None:
        path = self._vote_path()
        if not path:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"term": self.term, "voted_for": self.voted_for}, f)
        os.replace(tmp, path)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        t = threading.Thread(target=self._ticker, daemon=True,
                             name="raft-ticker")
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            self._repl_gen += 1
            for ev in self._repl_events.values():
                ev.set()
            self._commit_cv.notify_all()
        for c in self._clients.values():
            try:
                c.close()
            except Exception:
                pass

    # -- helpers -------------------------------------------------------
    def _new_deadline(self) -> float:
        return time.monotonic() + random.uniform(ELECTION_MIN_S,
                                                 ELECTION_MAX_S)

    def is_leader(self) -> bool:
        return self.role == LEADER

    def last_log(self) -> Tuple[int, int]:
        with self._lock:
            if self.log:
                e = self.log[-1]
                return e[0], e[1]
            return self.base_index, self.base_term

    def _client(self, addr: str):
        from ..rpc.client import RpcClient
        c = self._clients.get(addr)
        if c is None:
            c = RpcClient(addr, dial_timeout_s=1.0)
            self._clients[addr] = c
        return c

    # -- the leader append hook (called from Server.raft_apply) --------
    def record_entry(self, index: int, msg_type: str,
                     payload: dict) -> int:
        """Append a leader log entry; returns the term it was stamped
        with. Raises if this node is no longer the leader — a deposed
        leader must NOT append (the entry would carry the new term, so a
        follower would treat the real leader's entry at that index as
        already present and silently diverge)."""
        with self._lock:
            if self.role != LEADER:
                raise RuntimeError("not the leader")
            term = self.term
            self.log.append((index, term, msg_type,
                             encode_payload(msg_type, payload)))
            if not self.peers:
                self._advance_commit()
            for ev in self._repl_events.values():
                ev.set()
            return term

    # -- quorum commit -------------------------------------------------
    def _advance_commit(self) -> None:
        """Advance the commit index to the highest entry a majority
        holds, restricted to current-term entries (Raft §5.4.2). Called
        with self._lock held."""
        if self.role != LEADER:
            return
        last, _ = (self.log[-1][0], self.log[-1][1]) if self.log \
            else (self.base_index, self.base_term)
        matches = sorted(
            [self._match_index.get(p, 0) for p in self.peers] + [last],
            reverse=True)
        n = matches[self.cluster_size // 2]
        if n <= self.commit_index:
            return
        if n > self.base_index:
            pos = n - self.base_index - 1
            if pos < len(self.log) and self.log[pos][1] != self.term:
                return          # figure-8 guard: never count replicas
                                # to commit a prior-term entry
        self.commit_index = n
        self._commit_cv.notify_all()

    def wait_for_commit(self, index: int, term: Optional[int] = None,
                        timeout_s: float = 10.0) -> None:
        """Block until `index` is replicated to a majority. Raises if
        leadership is lost, the quorum is unreachable, or (when `term`
        is given) the node's term has moved past the one the entry was
        stamped with — a stepdown + reseed + re-election in between
        means the entry may no longer exist even though commit_index
        eventually passes it. The caller must not treat the write as
        durable on any raise."""
        if not self.peers:
            return
        deadline = time.monotonic() + timeout_s
        with self._commit_cv:
            while self.commit_index < index:
                if self._stop.is_set():
                    raise RuntimeError("raft node stopped")
                if self.role != LEADER:
                    raise RuntimeError(
                        f"leadership lost before commit of {index}")
                if term is not None and self.term != term:
                    raise RuntimeError(
                        f"term moved ({term} -> {self.term}) before "
                        f"commit of {index}")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RuntimeError(
                        f"no quorum: commit of {index} timed out "
                        f"after {timeout_s}s")
                self._commit_cv.wait(remaining)
            if term is not None and self.term != term:
                raise RuntimeError(
                    f"term moved ({term} -> {self.term}); entry {index} "
                    "may have been superseded")

    # -- follower write forwarding ------------------------------------
    def forward_apply(self, msg_type: str, payload: dict,
                      timeout_s: float = 15.0) -> int:
        leader = self.leader_addr
        if not leader:
            raise RuntimeError("no cluster leader")
        res = self._client(leader).call(
            "Raft.Forward",
            {"msg_type": msg_type,
             "payload": encode_payload(msg_type, payload)},
            timeout_s=timeout_s)
        return int(res["index"])

    def forward_rpc(self, method: str, args: dict, timeout_s: float = 30.0):
        leader = self.leader_addr
        if not leader:
            raise RuntimeError("no cluster leader")
        return self._client(leader).call(method, args, timeout_s=timeout_s)

    # -- role transitions ----------------------------------------------
    def _become_follower(self, term: int, leader: Optional[str]) -> None:
        was_leader = self.role == LEADER
        self.role = FOLLOWER
        self._repl_gen += 1            # retire replication threads
        self._repl_events.clear()
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._save_vote_state()
        if leader:
            self.leader_addr = leader
        self._election_deadline = self._new_deadline()
        self._commit_cv.notify_all()   # fail pending acks fast
        if was_leader:
            LOG.warning("stepping down (term %d)", self.term)
            self.server.revoke_leadership()

    def _become_leader(self) -> None:
        self.role = LEADER
        self.leader_addr = self.self_addr
        last, _ = self.last_log()
        self._next_index = {p: last + 1 for p in self.peers}
        self._match_index = {p: 0 for p in self.peers}
        self._repl_gen += 1
        gen = self._repl_gen
        self._repl_events = {}
        for peer in self.peers:
            ev = threading.Event()
            ev.set()
            self._repl_events[peer] = ev
            # not retained: retired generations exit via the gen check,
            # and retaining them would grow without bound under flapping
            threading.Thread(target=self._repl_loop,
                             args=(peer, gen, ev), daemon=True,
                             name=f"raft-repl-{peer}").start()
        LOG.warning("elected leader (term %d)", self.term)
        self.server.establish_leadership()
        if self.peers:
            # current-term no-op so prior-term entries become
            # committable (§5.4.2; hashicorp/raft appends LogNoop)
            threading.Thread(target=self._append_noop, daemon=True,
                             name="raft-noop").start()

    def _append_noop(self) -> None:
        try:
            self.server.raft_apply("noop", {})
        except Exception as e:      # stepped down again before commit
            LOG.debug("noop append failed: %s", e)

    # -- ticker: election timeouts (replication is per-peer threads) ---
    def _ticker(self) -> None:
        while not self._stop.is_set():
            time.sleep(HEARTBEAT_S / 2)
            with self._lock:
                role = self.role
            if role != LEADER and \
                    time.monotonic() > self._election_deadline:
                self._run_election()

    def _run_election(self) -> None:
        with self._lock:
            self.role = CANDIDATE
            self.term += 1
            self.voted_for = self.self_addr
            self._save_vote_state()
            term = self.term
            self._election_deadline = self._new_deadline()
        last_index, last_term = self.last_log()
        votes = 1
        for peer in self.peers:
            try:
                res = self._client(peer).call(
                    "Raft.RequestVote",
                    {"term": term, "candidate": self.self_addr,
                     "last_log_index": last_index,
                     "last_log_term": last_term},
                    timeout_s=0.5)
            except Exception:
                continue
            with self._lock:
                if res["term"] > self.term:
                    self._become_follower(res["term"], None)
                    return
            if res.get("granted"):
                votes += 1
        with self._lock:
            if self.role == CANDIDATE and self.term == term and \
                    votes * 2 > self.cluster_size:
                self._become_leader()

    # -- leader replication: one thread per peer ----------------------
    def _repl_loop(self, peer: str, gen: int,
                   wake: threading.Event) -> None:
        """Dedicated replication pump for one peer (hashicorp/raft
        replication.go). Wakes on new entries or every heartbeat
        interval; keeps draining while the peer is behind. A stuck or
        snapshotting peer only ever blocks its own thread."""
        while not self._stop.is_set():
            with self._lock:
                if self.role != LEADER or self._repl_gen != gen:
                    return
            wake.wait(HEARTBEAT_S)
            wake.clear()
            try:
                while self._replicate_peer(peer):
                    pass
            except Exception as e:
                LOG.debug("replicate to %s failed: %s", peer, e)
                time.sleep(HEARTBEAT_S / 2)     # redial backoff

    def _replicate_peer(self, peer: str) -> bool:
        """One AppendEntries (or snapshot) round trip. Returns True if
        the peer still has a backlog and the caller should continue."""
        with self._lock:
            if self.role != LEADER:
                return False
            term = self.term
            next_idx = self._next_index.get(peer, self.base_index + 1)
            if next_idx <= self.base_index:
                self._send_snapshot(peer, term)
                return False
            offset = next_idx - self.base_index - 1
            entries = self.log[offset:offset + MAX_BATCH]
            if offset > len(self.log):
                entries = []
            if offset == 0:
                prev_index, prev_term = self.base_index, self.base_term
            elif offset - 1 < len(self.log):
                e = self.log[offset - 1]
                prev_index, prev_term = e[0], e[1]
            else:
                last = self.log[-1] if self.log else None
                prev_index = last[0] if last else self.base_index
                prev_term = last[1] if last else self.base_term
            commit = min(self.commit_index,
                         self.log[-1][0] if self.log else self.base_index)
        res = self._client(peer).call(
            "Raft.AppendEntries",
            {"term": term, "leader": self.self_addr,
             "prev_index": prev_index, "prev_term": prev_term,
             "entries": [[e[0], e[1], e[2], e[3]] for e in entries],
             "leader_commit": commit},
            timeout_s=5.0)
        with self._lock:
            if res["term"] > self.term:
                self._become_follower(res["term"], None)
                return False
            if self.role != LEADER or self.term != term:
                return False
            if res.get("needs_snapshot"):
                self._send_snapshot(peer, term)
                return False
            if res.get("success"):
                matched = entries[-1][0] if entries else prev_index
                if matched > self._match_index.get(peer, 0):
                    self._match_index[peer] = matched
                    self._advance_commit()
                if entries:
                    self._next_index[peer] = matched + 1
                last = self.log[-1][0] if self.log else self.base_index
                return self._next_index.get(peer, last + 1) <= last
            self._next_index[peer] = max(
                self.base_index + 1,
                min(self._next_index.get(peer, 1) - 1,
                    int(res.get("hint", 0)) + 1))
            return True

    def _term_of(self, index: int) -> int:
        """Term of a log entry by index (lock held); base_term for the
        compaction base or anything at/below it."""
        pos = index - self.base_index - 1
        if 0 <= pos < len(self.log):
            return self.log[pos][1]
        return self.base_term

    def _send_snapshot(self, peer: str, term: int) -> None:
        """Full-state reseed of a lagging peer. The serialization + long
        transfer run with the raft lock RELEASED — only this peer's
        replication thread blocks on it. The snapshot's base index is
        captured atomically with an O(1) MVCC store snapshot under the
        server's apply lock (no apply in flight => applied state ==
        raft index == log tail), so the label can never run ahead of
        the state it describes — a too-high base would make followers
        skip committed entries forever."""
        self._lock.release()
        try:
            with self.server._raft_l:
                snap = self.server.store.snapshot()
                snap_index = self.server._raft_index
            with self._lock:
                if self.role != LEADER or self.term != term:
                    return
                snap_term = self._term_of(snap_index)
            data = snap.dump()
            res = self._client(peer).call(
                "Raft.InstallSnapshot",
                {"term": term, "leader": self.self_addr,
                 "snapshot": data, "base_index": snap_index,
                 "base_term": snap_term},
                timeout_s=30.0)
        finally:
            self._lock.acquire()
        if res["term"] > self.term:
            self._become_follower(res["term"], None)
            return
        if self.role != LEADER or self.term != term:
            return
        self._next_index[peer] = snap_index + 1
        if snap_index > self._match_index.get(peer, 0):
            self._match_index[peer] = snap_index
            self._advance_commit()

    # -- compaction ----------------------------------------------------
    def compact(self, keep: int = 4096) -> None:
        with self._lock:
            if len(self.log) <= keep:
                return
            drop = len(self.log) - keep
            e = self.log[drop - 1]
            self.base_index, self.base_term = e[0], e[1]
            self.log = self.log[drop:]

    # -- RPC handlers --------------------------------------------------
    def rpc_methods(self) -> Dict:
        return {
            "Raft.RequestVote": self._handle_request_vote,
            "Raft.AppendEntries": self._handle_append_entries,
            "Raft.InstallSnapshot": self._handle_install_snapshot,
            "Raft.Forward": self._handle_forward,
            "Raft.Status": self._handle_status,
        }

    def _handle_status(self, _args) -> dict:
        with self._lock:
            last_index, last_term = self.last_log()
            return {"role": self.role, "term": self.term,
                    "leader": self.leader_addr,
                    "last_log_index": last_index,
                    "last_log_term": last_term}

    def _handle_request_vote(self, args: dict) -> dict:
        term = int(args["term"])
        candidate = args["candidate"]
        with self._lock:
            if term > self.term:
                self._become_follower(term, None)
            if term < self.term:
                return {"term": self.term, "granted": False}
            last_index, last_term = self.last_log()
            up_to_date = (args["last_log_term"], args["last_log_index"]) \
                >= (last_term, last_index)
            if up_to_date and self.voted_for in (None, candidate):
                self.voted_for = candidate
                self._save_vote_state()
                self._election_deadline = self._new_deadline()
                return {"term": self.term, "granted": True}
            return {"term": self.term, "granted": False}

    def _handle_append_entries(self, args: dict) -> dict:
        term = int(args["term"])
        with self._lock:
            if term < self.term:
                return {"term": self.term, "success": False}
            if term > self.term or self.role != FOLLOWER:
                self._become_follower(term, args["leader"])
            self.leader_addr = args["leader"]
            self._election_deadline = self._new_deadline()
            if self.needs_snapshot:
                return {"term": self.term, "success": False,
                        "needs_snapshot": True}

            prev_index = int(args["prev_index"])
            prev_term = int(args["prev_term"])
            last_index, _ = self.last_log()
            applied = self.server._raft_index
            # consistency check at prev_index
            if prev_index > last_index:
                return {"term": self.term, "success": False,
                        "hint": last_index}
            if prev_index > self.base_index:
                e = self.log[prev_index - self.base_index - 1]
                if e[1] != prev_term:
                    # conflicting suffix: applied state cannot be
                    # unwound -> full reseed
                    if prev_index <= applied:
                        self.needs_snapshot = True
                        return {"term": self.term, "success": False,
                                "needs_snapshot": True}
                    del self.log[prev_index - self.base_index - 1:]
                    return {"term": self.term, "success": False,
                            "hint": prev_index - 1}
            elif prev_index < self.base_index:
                return {"term": self.term, "success": False,
                        "needs_snapshot": True}

            to_apply = []
            for idx, eterm, mtype, enc in args.get("entries", []):
                idx = int(idx)
                pos = idx - self.base_index - 1
                if pos < len(self.log):
                    if self.log[pos][1] == eterm:
                        continue                  # already have it
                    if idx <= applied:
                        self.needs_snapshot = True
                        return {"term": self.term, "success": False,
                                "needs_snapshot": True}
                    del self.log[pos:]
                self.log.append((idx, int(eterm), mtype, enc))
                to_apply.append((idx, mtype, enc))
        # apply outside the raft lock (FSM has its own serialization)
        for idx, mtype, enc in to_apply:
            if idx > self.server._raft_index:
                self.server.apply_replicated(idx, mtype, enc)
        return {"term": self.term, "success": True}

    def _handle_install_snapshot(self, args: dict) -> dict:
        term = int(args["term"])
        with self._lock:
            if term < self.term:
                return {"term": self.term}
            self._become_follower(term, args["leader"])
            self._election_deadline = self._new_deadline()
        self.server.install_snapshot(args["snapshot"])
        with self._lock:
            self.base_index = int(args["base_index"])
            self.base_term = int(args["base_term"])
            self.log = []
            self.needs_snapshot = False
        LOG.warning("installed snapshot at index %d", self.base_index)
        return {"term": self.term}

    def _handle_forward(self, args: dict) -> dict:
        if not self.is_leader():
            raise RuntimeError("not the leader")
        payload = decode_payload(args["msg_type"], args["payload"])
        index = self.server.raft_apply(args["msg_type"], payload)
        return {"index": index}
