"""Multi-server consensus: leader election + log replication + snapshot
install over the wire RPC layer.

Reference shape: nomad/server.go setupRaft:1214 (hashicorp/raft),
nomad/leader.go monitorLeadership:54 (establish/revoke hooks),
nomad/fsm.go Snapshot/Restore:1360-1374, rpc.go forward() (writes go to
the leader). SURVEY §7.2 step 7 blesses a "single-leader Raft-lite":

  - terms + randomized election timeouts + majority votes with the
    log-up-to-date check (Raft §5.2/§5.4.1); vote RPCs are issued in
    parallel (hashicorp/raft electSelf) so unreachable peers cannot
    stretch one election round past the election timeout
  - **apply-at-commit**: the leader appends entries to its log but the
    FSM applies them only once the commit index covers them — leader
    and follower share one applier loop (_fsm_loop), so neither role
    can ever serve reads or publish change events for a write that a
    majority does not hold (hashicorp/raft processLogs runs the FSM
    only up to commitIndex)
  - **commit means commit**: the leader acks a write only once a
    majority of the cluster holds the entry (match-index quorum over
    per-peer replication threads, Raft §5.3/§5.4) AND the local FSM has
    applied it, with the current-term commit rule (§5.4.2, figure 8)
    enforced via a no-op entry appended on election (the hashicorp/raft
    noop). A leader that cannot reach a majority times out the ack
    instead of claiming durability
  - replication runs in one dedicated thread per peer (hashicorp/raft
    replication.go shape) so a dead peer or an in-flight snapshot
    install can never starve heartbeats to healthy followers
  - because only committed entries reach the FSM, a follower's
    conflicting uncommitted suffix truncates freely (Raft §5.3); a
    full snapshot reseed (store.dump()/restore()) is needed only when
    the leader's log has been compacted past what the follower needs
  - membership is static configuration (no serf/autopilot)

Write forwarding: a non-leader server forwards (msg_type, payload)
through Raft.Forward; the client-facing RPC layer additionally forwards
whole write RPCs to the leader (rpc.go forward()).
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from .persistence import decode_payload, encode_payload
from ..chaos import faults as chaos_faults
from ..rpc.codec import RpcRefused
from ..utils.locks import make_condition, make_lock, make_rlock

LOG = logging.getLogger("nomad_tpu.raft")

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"

HEARTBEAT_S = 0.1
ELECTION_MIN_S = 0.5
ELECTION_MAX_S = 1.0
MAX_BATCH = 256


class RaftNode:
    def __init__(self, server, self_addr: str, peers: List[str],
                 data_dir: str = ""):
        self.server = server
        self.self_addr = self_addr
        self.peers = [p for p in peers if p != self_addr]
        self.cluster_size = len(self.peers) + 1
        self.data_dir = data_dir

        self._lock = make_rlock()
        self.role = FOLLOWER
        self.term = 0
        self.voted_for: Optional[str] = None
        self.leader_addr: Optional[str] = None
        # entries AFTER the compaction base: (index, term, type, enc)
        self.log: List[Tuple[int, int, str, dict]] = []
        self.base_index = server._raft_index
        self.base_term = 0
        self.needs_snapshot = False
        self.removed = False        # kicked from membership -> inert

        self._last_heartbeat = time.monotonic()
        self._election_deadline = self._new_deadline()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # per-peer replication state (leader)
        self._next_index: Dict[str, int] = {}
        self._match_index: Dict[str, int] = {}
        self._clients: Dict[str, object] = {}
        # quorum commit tracking: an entry is committed once a majority
        # of match indexes cover it and it belongs to the current term
        self.commit_index = self.base_index
        self._commit_cv = make_condition(self._lock)
        self._repl_gen = 0            # invalidates stale repl threads
        self._repl_events: Dict[str, threading.Event] = {}
        self._snap_gen = 0            # invalidates an in-flight FSM batch
        # last successful replication round trip per peer (leader):
        # the autopilot's liveness signal (nomad/autopilot.go reads
        # serf health; here replication contact plays that role)
        self.last_contact: Dict[str, float] = {}
        self._load_vote_state()

    # -- persistence of (term, votedFor) — Raft §5.1 -------------------
    def _vote_path(self) -> str:
        return os.path.join(self.data_dir, "raft_vote.json") \
            if self.data_dir else ""

    def _load_vote_state(self) -> None:
        path = self._vote_path()
        if not path or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                d = json.load(f)
            self.term = int(d.get("term", 0))
            self.voted_for = d.get("voted_for")
        except (OSError, json.JSONDecodeError):
            pass

    def _save_vote_state(self) -> None:
        path = self._vote_path()
        if not path:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"term": self.term, "voted_for": self.voted_for}, f)
        os.replace(tmp, path)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        t = threading.Thread(target=self._ticker, daemon=True,
                             name="raft-ticker")
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._fsm_loop, daemon=True,
                             name="raft-fsm")
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            self._repl_gen += 1
            for ev in self._repl_events.values():
                ev.set()
            self._commit_cv.notify_all()
        for c in self._clients.values():
            try:
                c.close()
            except Exception:
                pass

    # -- helpers -------------------------------------------------------
    def _new_deadline(self) -> float:
        return time.monotonic() + random.uniform(ELECTION_MIN_S,
                                                 ELECTION_MAX_S)

    def is_leader(self) -> bool:
        return self.role == LEADER

    def last_log(self) -> Tuple[int, int]:
        with self._lock:
            if self.log:
                e = self.log[-1]
                return e[0], e[1]
            return self.base_index, self.base_term

    def _client(self, addr: str):
        from ..rpc.client import RpcClient
        c = self._clients.get(addr)
        if c is None:
            c = RpcClient(addr, dial_timeout_s=1.0)
            self._clients[addr] = c
        return c

    # -- the leader append hook (called from Server.raft_apply) --------
    def append_entry(self, msg_type: str, payload: dict) -> Tuple[int, int]:
        """Append a leader log entry; assigns the next log index and
        returns (index, term). The FSM does NOT run here — _fsm_loop
        applies the entry once it commits. Raises if this node is no
        longer the leader — a deposed leader must NOT append (the entry
        would carry the new term, so a follower would treat the real
        leader's entry at that index as already present and silently
        diverge)."""
        with self._lock:
            if self.role != LEADER:
                raise RuntimeError("not the leader")
            last, _ = (self.log[-1][0], self.log[-1][1]) if self.log \
                else (self.base_index, self.base_term)
            index = last + 1
            term = self.term
            self.log.append((index, term, msg_type,
                             encode_payload(msg_type, payload)))
            if not self.peers:
                self._advance_commit()
            for ev in self._repl_events.values():
                ev.set()
            return index, term

    # -- quorum commit -------------------------------------------------
    def _advance_commit(self) -> None:
        """Advance the commit index to the highest entry a majority
        holds, restricted to current-term entries (Raft §5.4.2). Called
        with self._lock held."""
        if self.role != LEADER:
            return
        last, _ = (self.log[-1][0], self.log[-1][1]) if self.log \
            else (self.base_index, self.base_term)
        matches = sorted(
            [self._match_index.get(p, 0) for p in self.peers] + [last],
            reverse=True)
        n = matches[self.cluster_size // 2]
        if n <= self.commit_index:
            return
        if n > self.base_index:
            pos = n - self.base_index - 1
            if pos < len(self.log) and self.log[pos][1] != self.term:
                return          # figure-8 guard: never count replicas
                                # to commit a prior-term entry
        self.commit_index = n
        self._commit_cv.notify_all()

    # -- committed-entry FSM applier (leader and follower) -------------
    def _fsm_loop(self) -> None:
        """Single applier: runs the FSM over entries in log order as the
        commit index advances — hashicorp/raft runFSM/processLogs. The
        apply itself runs with the raft lock RELEASED (the FSM has its
        own serialization and may re-enter append_entry for side-effect
        writes); a snapshot install invalidates the in-flight batch via
        _snap_gen."""
        while not self._stop.is_set():
            with self._commit_cv:
                while (not self._stop.is_set()
                       and self.commit_index <= self.server._raft_index):
                    self._commit_cv.wait(0.5)
                if self._stop.is_set():
                    return
                gen = self._snap_gen
                applied = max(self.server._raft_index, self.base_index)
                start = applied - self.base_index
                stop = min(self.commit_index - self.base_index,
                           len(self.log))
                batch = list(self.log[start:stop])
            if not batch:
                # committed entries we don't hold yet (post-reseed gap);
                # replication refills the log shortly
                time.sleep(HEARTBEAT_S / 4)
                continue
            for idx, _eterm, mtype, enc in batch:
                with self._lock:
                    if self._snap_gen != gen:
                        break
                try:
                    self.server.apply_replicated(idx, mtype, enc)
                except Exception:
                    # an applier error must not kill the ONLY applier
                    # thread (that would wedge the node forever while
                    # the commit index keeps advancing). The entry is
                    # counted applied — the reference FSM logs apply
                    # errors and moves on too (a deterministic error
                    # fails identically on every replica)
                    LOG.exception("FSM apply of entry %d (%s) failed",
                                  idx, mtype)
                    with self.server._raft_l:
                        if self.server._raft_index < idx:
                            self.server._raft_index = idx
            # group-fsync barrier (ISSUE 8): the committed batch is the
            # WAL's commit unit — one fsync covers every entry recorded
            # above instead of one per frame (wal_group_fsync)
            if self.server.persistence is not None:
                try:
                    self.server.persistence.commit_barrier()
                except OSError:     # pragma: no cover — best effort
                    LOG.exception("WAL group fsync failed")
            with self._commit_cv:
                self._commit_cv.notify_all()   # wake wait_for_applied

    def wait_for_applied(self, index: int, term: Optional[int] = None,
                         timeout_s: float = 10.0) -> None:
        """Block until `index` is replicated to a majority AND applied
        by the local FSM. Raises if leadership is lost or the term moves
        before the entry commits (a stepdown + truncation in between
        means the entry may no longer exist), or on quorum timeout. The
        caller must not treat the write as durable on any raise. Once
        the entry is committed in the term it was stamped with, it is
        durable — the remaining wait is only for the local applier to
        catch up, and survives role changes."""
        deadline = time.monotonic() + timeout_s
        with self._commit_cv:
            while self.commit_index < index:
                if self._stop.is_set():
                    raise RpcRefused("raft node stopped")
                if self.role != LEADER:
                    # stepdown mid-wait: a protocol outcome — the
                    # caller must treat the write as not durable and
                    # retry through the new leader; RpcRefused keeps
                    # forwarded writes traceback-free in the dispatcher
                    raise RpcRefused(
                        f"leadership lost before commit of {index}")
                if term is not None and self.term != term:
                    raise RpcRefused(
                        f"term moved ({term} -> {self.term}) before "
                        f"commit of {index}")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RuntimeError(
                        f"no quorum: commit of {index} timed out "
                        f"after {timeout_s}s")
                self._commit_cv.wait(remaining)
            # committed: verify it is still OUR entry (the log cannot
            # have been truncated below the commit index, but a
            # stepdown + reseed may have replaced and compacted it —
            # base_index == index with a different base_term means the
            # NEW leader's entry took our index)
            if term is not None:
                if index <= self.base_index:
                    if self.role == LEADER and self.term == term:
                        pass    # a leader never loses its own entry
                                # while it stays leader in that term
                    elif index == self.base_index and \
                            self.base_term == term:
                        pass
                    else:
                        raise RuntimeError(
                            f"entry {index} compacted/superseded; "
                            f"cannot verify term {term}")
                elif self._term_of(index) != term:
                    raise RuntimeError(
                        f"entry {index} superseded (term {term} -> "
                        f"{self._term_of(index)})")
            while self.server._raft_index < index:
                if self._stop.is_set():
                    raise RpcRefused("raft node stopped")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RuntimeError(
                        f"apply of committed entry {index} timed out")
                self._commit_cv.wait(remaining)

    # -- follower write forwarding ------------------------------------
    def forward_apply(self, msg_type: str, payload: dict,
                      timeout_s: float = 15.0) -> int:
        leader = self.leader_addr
        if not leader:
            raise RpcRefused("no cluster leader")
        res = self._client(leader).call(
            "Raft.Forward",
            {"msg_type": msg_type,
             "payload": encode_payload(msg_type, payload)},
            timeout_s=timeout_s)
        return int(res["index"])

    def forward_rpc(self, method: str, args: dict, timeout_s: float = 30.0):
        leader = self.leader_addr
        if not leader:
            raise RpcRefused("no cluster leader")
        return self._client(leader).call(method, args, timeout_s=timeout_s)

    # -- role transitions ----------------------------------------------
    def _become_follower(self, term: int, leader: Optional[str]) -> None:
        was_leader = self.role == LEADER
        self.role = FOLLOWER
        self._repl_gen += 1            # retire replication threads
        self._repl_events.clear()
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._save_vote_state()
        if leader:
            self.leader_addr = leader
        self._election_deadline = self._new_deadline()
        self._commit_cv.notify_all()   # fail pending acks fast
        if was_leader:
            LOG.warning("stepping down (term %d)", self.term)
            self.server.revoke_leadership()

    def _become_leader(self) -> None:
        self.role = LEADER
        self.leader_addr = self.self_addr
        last, _ = self.last_log()
        self._next_index = {p: last + 1 for p in self.peers}
        self._match_index = {p: 0 for p in self.peers}
        # autopilot's contact clock starts at election for EVERY peer:
        # a server that died before this term would otherwise default
        # to age 0 forever and never be reaped
        now = time.monotonic()
        for p in self.peers:
            self.last_contact.setdefault(p, now)
        self._repl_gen += 1
        gen = self._repl_gen
        self._repl_events = {}
        for peer in self.peers:
            ev = threading.Event()
            ev.set()
            self._repl_events[peer] = ev
            # not retained: retired generations exit via the gen check,
            # and retaining them would grow without bound under flapping
            threading.Thread(target=self._repl_loop,
                             args=(peer, gen, ev), daemon=True,
                             name=f"raft-repl-{peer}").start()
        LOG.warning("elected leader (term %d)", self.term)
        self.server.establish_leadership()
        if self.peers:
            # current-term no-op so prior-term entries become
            # committable (§5.4.2; hashicorp/raft appends LogNoop)
            threading.Thread(target=self._append_noop, daemon=True,
                             name="raft-noop").start()

    def _append_noop(self) -> None:
        try:
            self.server.raft_apply("noop", {})
        except Exception as e:      # stepped down again before commit
            LOG.debug("noop append failed: %s", e)

    # -- ticker: election timeouts (replication is per-peer threads) ---
    def _ticker(self) -> None:
        while not self._stop.is_set():
            time.sleep(HEARTBEAT_S / 2)
            with self._lock:
                role = self.role
                if self.removed:
                    continue        # inert: never campaign
            if role != LEADER and \
                    time.monotonic() > self._election_deadline:
                if chaos_faults.ACTIVE and chaos_faults.fire(
                        "raft.election", addr=self.self_addr):
                    # chaos hook (ISSUE 16 follower_fence cell): a
                    # replication-lagged victim must STAY a lagging
                    # follower — without this its missed heartbeats
                    # would trigger a campaign, bump the term, and
                    # depose the very leader the cell is measuring
                    self._election_deadline = self._new_deadline()
                    continue
                self._run_election()

    def _run_election(self) -> None:
        """One election round with PARALLEL vote RPCs (hashicorp/raft
        electSelf): unreachable peers cost nothing extra — the round
        lasts at most one vote-RPC timeout, not one per dead peer."""
        with self._lock:
            self.role = CANDIDATE
            self.term += 1
            self.voted_for = self.self_addr
            self._save_vote_state()
            term = self.term
            self._election_deadline = self._new_deadline()
        last_index, last_term = self.last_log()
        tally_l = make_lock()
        votes = [1]                       # self-vote
        higher_term = [0]
        outcome = threading.Event()       # majority reached or must step down

        def ask(peer: str) -> None:
            try:
                res = self._client(peer).call(
                    "Raft.RequestVote",
                    {"term": term, "candidate": self.self_addr,
                     "last_log_index": last_index,
                     "last_log_term": last_term},
                    timeout_s=0.5)
            except Exception:
                return
            with tally_l:
                if res["term"] > term:
                    higher_term[0] = max(higher_term[0], res["term"])
                    outcome.set()
                    return
                if res.get("granted"):
                    votes[0] += 1
                    if votes[0] * 2 > self.cluster_size:
                        outcome.set()

        for peer in self.peers:
            threading.Thread(target=ask, args=(peer,), daemon=True,
                             name=f"raft-vote-{peer}").start()
        outcome.wait(0.6)
        with self._lock:
            with tally_l:
                bumped, got = higher_term[0], votes[0]
            if bumped > self.term:
                self._become_follower(bumped, None)
                return
            if self.role == CANDIDATE and self.term == term and \
                    got * 2 > self.cluster_size:
                self._become_leader()

    # -- leader replication: one thread per peer ----------------------
    def _repl_loop(self, peer: str, gen: int,
                   wake: threading.Event) -> None:
        """Dedicated replication pump for one peer (hashicorp/raft
        replication.go). Wakes on new entries or every heartbeat
        interval; keeps draining while the peer is behind. A stuck or
        snapshotting peer only ever blocks its own thread."""
        while not self._stop.is_set():
            with self._lock:
                if self.role != LEADER or self._repl_gen != gen \
                        or self._repl_events.get(peer) is not wake:
                    return          # retired or membership removed us
            wake.wait(HEARTBEAT_S)
            wake.clear()
            try:
                while self._replicate_peer(peer):
                    pass
            except Exception as e:
                LOG.debug("replicate to %s failed: %s", peer, e)
                time.sleep(HEARTBEAT_S / 2)     # redial backoff

    def _replicate_peer(self, peer: str) -> bool:
        """One AppendEntries (or snapshot) round trip. Returns True if
        the peer still has a backlog and the caller should continue."""
        if chaos_faults.ACTIVE and chaos_faults.fire("raft.replicate",
                                                     target=peer):
            # chaos hook (ISSUE 16): an armed replication-lag fault
            # drops this round trip on the LEADER side — the victim's
            # log (and store) falls behind while its process stays
            # healthy, which is exactly the state the follower snapshot
            # fence exists to handle. Interposing the victim's
            # AppendEntries handler instead would either hot-loop the
            # pump (rejection => immediate resend) or corrupt the
            # leader's match-index accounting (fake success)
            return False
        with self._lock:
            if self.role != LEADER:
                return False
            term = self.term
            next_idx = self._next_index.get(peer, self.base_index + 1)
            if next_idx <= self.base_index:
                self._send_snapshot(peer, term)
                return False
            offset = next_idx - self.base_index - 1
            entries = self.log[offset:offset + MAX_BATCH]
            if offset > len(self.log):
                entries = []
            if offset == 0:
                prev_index, prev_term = self.base_index, self.base_term
            elif offset - 1 < len(self.log):
                e = self.log[offset - 1]
                prev_index, prev_term = e[0], e[1]
            else:
                last = self.log[-1] if self.log else None
                prev_index = last[0] if last else self.base_index
                prev_term = last[1] if last else self.base_term
            commit = min(self.commit_index,
                         self.log[-1][0] if self.log else self.base_index)
        res = self._client(peer).call(
            "Raft.AppendEntries",
            {"term": term, "leader": self.self_addr,
             "prev_index": prev_index, "prev_term": prev_term,
             "entries": [[e[0], e[1], e[2], e[3]] for e in entries],
             "leader_commit": commit},
            timeout_s=5.0)
        with self._lock:
            if res["term"] > self.term:
                self._become_follower(res["term"], None)
                return False
            if self.role != LEADER or self.term != term:
                return False
            if res.get("needs_snapshot"):
                self._send_snapshot(peer, term)
                return False
            if res.get("success"):
                self.last_contact[peer] = time.monotonic()
                matched = entries[-1][0] if entries else prev_index
                if matched > self._match_index.get(peer, 0):
                    self._match_index[peer] = matched
                    self._advance_commit()
                if entries:
                    self._next_index[peer] = matched + 1
                last = self.log[-1][0] if self.log else self.base_index
                return self._next_index.get(peer, last + 1) <= last
            self._next_index[peer] = max(
                self.base_index + 1,
                min(self._next_index.get(peer, 1) - 1,
                    int(res.get("hint", 0)) + 1))
            return True

    def _term_of(self, index: int) -> int:
        """Term of a log entry by index (lock held); base_term for the
        compaction base or anything at/below it."""
        pos = index - self.base_index - 1
        if 0 <= pos < len(self.log):
            return self.log[pos][1]
        return self.base_term

    def _send_snapshot(self, peer: str, term: int) -> None:
        """Full-state reseed of a lagging peer. The serialization + long
        transfer run with the raft lock RELEASED — only this peer's
        replication thread blocks on it. The snapshot's base index is
        the APPLIED index captured atomically with an O(1) MVCC store
        snapshot under the server's apply lock, so the label can never
        run ahead of the state it describes — a too-high base would
        make followers skip committed entries forever. (With
        apply-at-commit the applied index never exceeds the commit
        index, so the label also never covers an uncommitted entry.)"""
        self._lock.release()
        try:
            with self.server._raft_l:
                snap = self.server.store.snapshot()
                snap_index = self.server._raft_index
            with self._lock:
                if self.role != LEADER or self.term != term:
                    return
                snap_term = self._term_of(snap_index)
            data = snap.dump()
            res = self._client(peer).call(
                "Raft.InstallSnapshot",
                {"term": term, "leader": self.self_addr,
                 "snapshot": data, "base_index": snap_index,
                 "base_term": snap_term},
                timeout_s=30.0)
        finally:
            self._lock.acquire()
        if res["term"] > self.term:
            self._become_follower(res["term"], None)
            return
        if self.role != LEADER or self.term != term:
            return
        self._next_index[peer] = snap_index + 1
        if snap_index > self._match_index.get(peer, 0):
            self._match_index[peer] = snap_index
            self._advance_commit()

    # -- dynamic membership (nomad/serf.go + setupSerf; membership
    # itself rides the replicated log, liveness is leader-local) ------
    def update_members(self, members: List[str]) -> None:
        """Adopt a new replicated member list. New peers get
        replication threads (when leader); removed peers' pumps retire;
        quorum math follows the new cluster size. Called from the FSM
        applier, so every replica converges on the same view."""
        with self._lock:
            members = list(dict.fromkeys(members))
            if self.self_addr not in members:
                # we were removed (autopilot dead-server cleanup or an
                # operator leave): go INERT — no elections, no
                # self-cluster takeover (a left nomad server shuts its
                # raft down the same way)
                LOG.warning("removed from cluster membership; isolating")
                self.peers = []
                self.cluster_size = 1
                self.removed = True
                if self.role == LEADER:
                    self._become_follower(self.term, None)
                return
            self.removed = False
            new_peers = [m for m in members if m != self.self_addr]
            added = [p for p in new_peers if p not in self.peers]
            removed = [p for p in self.peers if p not in new_peers]
            self.peers = new_peers
            self.cluster_size = len(new_peers) + 1
            for peer in removed:
                self._repl_events.pop(peer, None)
                self._next_index.pop(peer, None)
                self._match_index.pop(peer, None)
                self.last_contact.pop(peer, None)
            if self.role == LEADER:
                last, _ = (self.log[-1][0], self.log[-1][1]) if self.log \
                    else (self.base_index, self.base_term)
                gen = self._repl_gen
                for peer in added:
                    self._next_index[peer] = last + 1
                    self._match_index[peer] = 0
                    self.last_contact[peer] = time.monotonic()
                    ev = threading.Event()
                    ev.set()
                    self._repl_events[peer] = ev
                    threading.Thread(target=self._repl_loop,
                                     args=(peer, gen, ev), daemon=True,
                                     name=f"raft-repl-{peer}").start()
                if removed:
                    self._advance_commit()

    # -- compaction ----------------------------------------------------
    def compact(self, keep: int = 4096) -> None:
        """Drop applied log prefix. Never compacts past the locally
        APPLIED index — the _fsm_loop still needs committed-but-
        unapplied entries, and a reseeded base above the applied state
        would reissue already-used indexes (the r3 advisor's
        index-below-base corruption)."""
        with self._lock:
            limit = min(self.server._raft_index, self.commit_index)
            if len(self.log) <= keep:
                return
            drop = len(self.log) - keep
            drop = min(drop, max(0, limit - self.base_index))
            if drop <= 0:
                return
            e = self.log[drop - 1]
            self.base_index, self.base_term = e[0], e[1]
            self.log = self.log[drop:]

    # -- RPC handlers --------------------------------------------------
    def rpc_methods(self) -> Dict:
        def gated(fn):
            # a stopped raft node must refuse RPCs: established
            # connections outlive the listener, and answering
            # AppendEntries after shutdown makes a "dead" server look
            # alive to the leader's contact clock (and to autopilot).
            # RpcRefused keeps the refusal an error on the caller's
            # side without tripping the dispatcher's traceback logging
            # — staggered ring teardown is a clean path (ISSUE 16)
            def handler(args):
                if self._stop.is_set():
                    raise RpcRefused("raft node stopped")
                return fn(args)
            return handler

        return {
            "Raft.RequestVote": gated(self._handle_request_vote),
            "Raft.AppendEntries": gated(self._handle_append_entries),
            "Raft.InstallSnapshot": gated(self._handle_install_snapshot),
            "Raft.Forward": gated(self._handle_forward),
            "Raft.Status": self._handle_status,
        }

    def _handle_status(self, _args) -> dict:
        with self._lock:
            last_index, last_term = self.last_log()
            return {"role": self.role, "term": self.term,
                    "leader": self.leader_addr,
                    "last_log_index": last_index,
                    "last_log_term": last_term,
                    "commit_index": self.commit_index,
                    "applied_index": self.server._raft_index}

    def _handle_request_vote(self, args: dict) -> dict:
        term = int(args["term"])
        candidate = args["candidate"]
        with self._lock:
            if term > self.term:
                self._become_follower(term, None)
            if term < self.term:
                return {"term": self.term, "granted": False}
            last_index, last_term = self.last_log()
            up_to_date = (args["last_log_term"], args["last_log_index"]) \
                >= (last_term, last_index)
            if up_to_date and self.voted_for in (None, candidate):
                self.voted_for = candidate
                self._save_vote_state()
                self._election_deadline = self._new_deadline()
                return {"term": self.term, "granted": True}
            return {"term": self.term, "granted": False}

    def _handle_append_entries(self, args: dict) -> dict:
        term = int(args["term"])
        with self._lock:
            if term < self.term:
                return {"term": self.term, "success": False}
            if term > self.term or self.role != FOLLOWER:
                self._become_follower(term, args["leader"])
            self.leader_addr = args["leader"]
            self._election_deadline = self._new_deadline()
            if self.needs_snapshot:
                return {"term": self.term, "success": False,
                        "needs_snapshot": True}

            prev_index = int(args["prev_index"])
            prev_term = int(args["prev_term"])
            last_index, _ = self.last_log()
            committed = max(self.commit_index, self.server._raft_index)
            # consistency check at prev_index
            if prev_index > last_index:
                return {"term": self.term, "success": False,
                        "hint": last_index}
            if prev_index > self.base_index:
                e = self.log[prev_index - self.base_index - 1]
                if e[1] != prev_term:
                    if prev_index <= committed:
                        # a committed entry can never conflict (leader
                        # completeness, §5.4.3) — if it appears to, our
                        # commit accounting is damaged: full reseed
                        self.needs_snapshot = True
                        return {"term": self.term, "success": False,
                                "needs_snapshot": True}
                    # uncommitted conflicting suffix truncates freely —
                    # nothing was applied (§5.3)
                    del self.log[prev_index - self.base_index - 1:]
                    return {"term": self.term, "success": False,
                            "hint": prev_index - 1}
            elif prev_index < self.base_index:
                return {"term": self.term, "success": False,
                        "needs_snapshot": True}

            for idx, eterm, mtype, enc in args.get("entries", []):
                idx = int(idx)
                if idx <= self.base_index:
                    continue        # covered by the installed snapshot
                pos = idx - self.base_index - 1
                if pos < len(self.log):
                    if self.log[pos][1] == eterm:
                        continue                  # already have it
                    if idx <= committed:
                        self.needs_snapshot = True
                        return {"term": self.term, "success": False,
                                "needs_snapshot": True}
                    del self.log[pos:]
                self.log.append((idx, int(eterm), mtype, enc))
            # follower commit rule (§5.3): commit up to the leader's
            # commit index, bounded by what we actually hold; _fsm_loop
            # applies from there — never before
            last_index, _ = self.last_log()
            new_commit = min(int(args.get("leader_commit", 0)), last_index)
            if new_commit > self.commit_index:
                self.commit_index = new_commit
                self._commit_cv.notify_all()
        return {"term": self.term, "success": True}

    def _handle_install_snapshot(self, args: dict) -> dict:
        term = int(args["term"])
        with self._lock:
            if term < self.term:
                return {"term": self.term}
            self._become_follower(term, args["leader"])
            self._election_deadline = self._new_deadline()
        base_index = int(args["base_index"])
        # restore the store AND pin the applied index to the snapshot's
        # base — store.latest_index() alone undercounts (no-op entries
        # touch no table), which would reissue already-used log indexes
        # if this node later won an election (r3 advisor, high)
        self.server.install_snapshot(args["snapshot"], base_index)
        with self._lock:
            self.base_index = base_index
            self.base_term = int(args["base_term"])
            self.log = []
            self.commit_index = base_index
            self.needs_snapshot = False
            self._snap_gen += 1       # invalidate in-flight FSM batch
            self._commit_cv.notify_all()
        LOG.warning("installed snapshot at index %d", self.base_index)
        return {"term": self.term}

    def _handle_forward(self, args: dict) -> dict:
        if not self.is_leader():
            # protocol refusal, not a fault: the forwarder rehomes to
            # the new leader (or its caller nacks and the eval is
            # redelivered) — no traceback for a routine stepdown
            raise RpcRefused("not the leader")
        payload = decode_payload(args["msg_type"], args["payload"])
        index = self.server.raft_apply(args["msg_type"], payload)
        return {"index": index}
