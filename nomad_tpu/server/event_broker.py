"""Event stream: change events from FSM commits, fan-out to subscribers.

Reference semantics: nomad/stream/event_broker.go (EventBroker:24,
Publish:76, Subscribe:94 — ring buffer + per-topic filtered
subscriptions), nomad/state/events.go (eventsFromChanges — mapping FSM
log types to topic/type/key events), and nomad/stream/ndjson.go (the
HTTP NDJSON bridge lives in api/http.py's /v1/event/stream route).

Topics mirror structs.TopicJob/Eval/Alloc/Deployment/Node; filter keys
are the object IDs. The ring buffer holds the last `size` event batches
so a new subscriber can replay recent history from a given index.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple
from ..utils.locks import make_condition, make_lock

TOPIC_JOB = "Job"
TOPIC_EVAL = "Evaluation"
TOPIC_ALLOC = "Allocation"
TOPIC_DEPLOYMENT = "Deployment"
TOPIC_NODE = "Node"
TOPIC_SERVICE = "Service"
TOPIC_ALL = "*"

ALL_KEYS = "*"


@dataclass
class Event:
    topic: str = ""
    type: str = ""              # e.g. JobRegistered, NodeDrain, PlanResult
    key: str = ""               # primary id (job id, node id, ...)
    namespace: str = ""
    index: int = 0
    payload: dict = field(default_factory=dict)

    def matches(self, topics: Dict[str, List[str]]) -> bool:
        for topic, keys in topics.items():
            if topic not in (TOPIC_ALL, self.topic):
                continue
            if not keys or ALL_KEYS in keys or self.key in keys:
                return True
        return False


class Subscription:
    """One consumer's view: a bounded queue of matching events."""

    def __init__(self, broker: "EventBroker", topics: Dict[str, List[str]],
                 max_queued: int = 1024):
        self._broker = broker
        self.topics = topics
        self._cond = make_condition()
        self._queue: List[Event] = []
        self._max = max_queued
        self.closed = False
        # set when the slow-consumer drop fires: consumers that promise
        # at-least-once (event sinks) must see overflow, not silence
        self.overflowed = False

    def deliver(self, events: List[Event]) -> None:
        matched = [e for e in events if e.matches(self.topics)]
        if not matched:
            return
        with self._cond:
            self._queue.extend(matched)
            if len(self._queue) > self._max:
                # drop oldest — a slow consumer must not block the broker
                del self._queue[:len(self._queue) - self._max]
                self.overflowed = True
            self._cond.notify_all()

    def next_events(self, timeout_s: float = 10.0) -> List[Event]:
        """Block until events arrive (or timeout -> empty list)."""
        with self._cond:
            if not self._queue:
                self._cond.wait(timeout_s)
            out, self._queue = self._queue, []
            return out

    def unsubscribe(self) -> None:
        self.closed = True
        self._broker._remove(self)
        with self._cond:
            self._cond.notify_all()


def approx_event_bytes(e: Event) -> int:
    """Cheap shallow estimate of an event's resident footprint. Exact
    byte accounting would serialize every payload on the hot publish
    path; the governor only needs a consistent order-of-magnitude
    gauge to bound the ring."""
    sz = 200
    p = e.payload
    if p:
        sz += 48 * len(p)
        for v in p.values():
            if isinstance(v, str):
                sz += len(v)
            elif isinstance(v, (list, dict)):
                sz += 64 * len(v)
    return sz


class EventBroker:
    # replay history is bounded by BYTES as well as count: a ring of
    # 4096 job-register events each dragging a full wire-encoded job
    # spec is tens of MB of history nobody asked for (round-5 soak RSS
    # drift); count alone never bounded that
    DEFAULT_MAX_BYTES = 16 << 20

    def __init__(self, size: int = 4096,
                 max_bytes: int = DEFAULT_MAX_BYTES):
        self._l = make_lock()
        self._buffer: List[Event] = []   # ring of recent events
        self._size = size
        self._max_bytes = max_bytes
        self._buffer_bytes = 0
        self._subs: List[Subscription] = []
        self.latest_index = 0
        # highest index ever dropped off the ring: a consumer resuming
        # from progress <= trimmed_through has a PROVEN replay gap
        self.trimmed_through = 0
        # indexes at or below this floor predate this broker's life
        # (set to the store's index once boot restore finishes; WAL
        # replay publishes no events) — progress below it cannot be
        # proven continuous either
        self.epoch_floor = 0

    def publish(self, events: List[Event]) -> None:
        if not events:
            return
        with self._l:
            self._buffer.extend(events)
            self._buffer_bytes += sum(approx_event_bytes(e)
                                      for e in events)
            self._trim_locked(self._size, self._max_bytes)
            self.latest_index = max(self.latest_index,
                                    max(e.index for e in events))
            subs = list(self._subs)
        for s in subs:
            try:
                s.deliver(events)
            except Exception:       # one bad filter must not starve
                import logging      # every later subscriber
                logging.getLogger("nomad_tpu.events").exception(
                    "subscriber delivery failed; unsubscribing it")
                self._remove(s)

    def subscribe(self, topics: Optional[Dict[str, List[str]]] = None,
                  from_index: int = 0,
                  max_queued: int = 1024) -> Tuple[Subscription, List[Event]]:
        """Returns the subscription plus any buffered events newer than
        from_index (replay for late joiners)."""
        topics = topics or {TOPIC_ALL: [ALL_KEYS]}
        sub = Subscription(self, topics, max_queued=max_queued)
        with self._l:
            backlog = [e for e in self._buffer
                       if e.index > from_index and e.matches(topics)]
            self._subs.append(sub)
        return sub, backlog


    def _remove(self, sub: Subscription) -> None:
        with self._l:
            if sub in self._subs:
                self._subs.remove(sub)

    # -- governance (governor/) ----------------------------------------
    def _trim_locked(self, max_events: int, max_bytes: int) -> None:
        """Drop oldest events until the ring fits both bounds,
        advancing trimmed_through so resumed consumers see a proven
        replay gap, never silence."""
        buf = self._buffer
        drop = 0
        dropped_bytes = 0
        n = len(buf)
        while n - drop > max_events or \
                self._buffer_bytes - dropped_bytes > max_bytes:
            if drop >= n:
                break
            dropped_bytes += approx_event_bytes(buf[drop])
            drop += 1
        if drop:
            self.trimmed_through = max(self.trimmed_through,
                                       buf[drop - 1].index)
            del buf[:drop]
            self._buffer_bytes = max(0, self._buffer_bytes
                                     - dropped_bytes)

    def truncate(self, fraction: float = 0.5) -> dict:
        """Governor reclaim: shed the oldest `fraction` of buffered
        history immediately (watermark breach), keeping replay
        correctness via trimmed_through."""
        with self._l:
            before = len(self._buffer)
            keep = max(0, int(before * (1.0 - fraction)))
            self._trim_locked(keep, self._max_bytes)
            return {"dropped_events": before - len(self._buffer),
                    "buffer_events": len(self._buffer)}

    def buffered_events(self) -> int:
        with self._l:
            return len(self._buffer)

    def buffered_bytes(self) -> int:
        with self._l:
            return self._buffer_bytes

    def stats(self) -> dict:
        with self._l:
            return {"events": len(self._buffer),
                    "approx_bytes": self._buffer_bytes,
                    "subscriptions": len(self._subs),
                    "latest_index": self.latest_index,
                    "trimmed_through": self.trimmed_through}


# -- FSM commit -> events (nomad/state/events.go eventsFromChanges) ----

def events_from_apply(msg_type: str, payload: dict, index: int) -> List[Event]:
    from ..utils.codec import to_wire
    out: List[Event] = []

    def add(topic, etype, key, namespace="", obj=None):
        out.append(Event(topic=topic, type=etype, key=key,
                         namespace=namespace, index=index,
                         payload=to_wire(obj) if obj is not None else {}))

    if msg_type == "job_register":
        job = payload["job"]
        add(TOPIC_JOB, "JobRegistered", job.id, job.namespace, job)
        # ingest-embedded register evals (ISSUE 19) ride the same entry
        for ev in payload.get("evals", []):
            add(TOPIC_EVAL, "EvaluationUpdated", ev.id, ev.namespace, ev)
    elif msg_type == "job_deregister":
        add(TOPIC_JOB, "JobDeregistered", payload["job_id"],
            payload["namespace"])
    elif msg_type == "eval_update":
        for ev in payload.get("evals", []):
            add(TOPIC_EVAL, "EvaluationUpdated", ev.id, ev.namespace, ev)
    elif msg_type == "node_register":
        node = payload["node"]
        add(TOPIC_NODE, "NodeRegistration", node.id)
    elif msg_type == "node_deregister":
        for nid in payload.get("node_ids", []):
            add(TOPIC_NODE, "NodeDeregistration", nid)
    elif msg_type == "node_status_update":
        add(TOPIC_NODE, "NodeStatusUpdate", payload["node_id"])
        out[-1].payload = {"status": payload.get("status", "")}
    elif msg_type == "node_drain_update":
        add(TOPIC_NODE, "NodeDrain", payload["node_id"])
    elif msg_type == "node_eligibility_update":
        add(TOPIC_NODE, "NodeEligibility", payload["node_id"])
        out[-1].payload = {"eligibility": payload.get("eligibility", "")}
    elif msg_type == "alloc_client_update":
        for a in payload.get("allocs", []):
            add(TOPIC_ALLOC, "AllocationUpdated", a.id, a.namespace)
            out[-1].payload = {"client_status": a.client_status}
        for ev in payload.get("evals", []):
            add(TOPIC_EVAL, "EvaluationUpdated", ev.id, ev.namespace, ev)
    elif msg_type == "alloc_desired_transition":
        for aid in payload.get("alloc_ids", []):
            add(TOPIC_ALLOC, "AllocationUpdateDesiredStatus", aid)
    elif msg_type == "ingest_batch":
        # one coalesced write entry, one flush: every sub-entry's
        # events publish together under its own kind (ISSUE 19, the
        # plan_group_results recursion pointed at the write front)
        for e in payload.get("entries", []):
            out.extend(events_from_apply(e.get("kind", ""), e, index))
    elif msg_type == "plan_group_results":
        # one committed entry, one flush: every group member's events
        # publish together (the per-plan event flush was part of the
        # per-eval host tax the group-commit applier amortizes)
        for g in payload.get("groups", []):
            out.extend(events_from_apply("plan_results", g, index))
    elif msg_type == "plan_results":
        for a in payload.get("allocs_placed", []):
            add(TOPIC_ALLOC, "PlanResult", a.id, a.namespace)
        for a in payload.get("allocs_stopped", []):
            add(TOPIC_ALLOC, "AllocationUpdateDesiredStatus", a.id,
                a.namespace)
        d = payload.get("deployment")
        if d is not None:
            add(TOPIC_DEPLOYMENT, "DeploymentStatusUpdate", d.id,
                d.namespace, d)
    elif msg_type == "deployment_status_update":
        u = payload["update"]
        add(TOPIC_DEPLOYMENT, "DeploymentStatusUpdate", u.deployment_id)
        out[-1].payload = {"status": u.status,
                           "status_description": u.status_description}
    elif msg_type == "deployment_promotion":
        add(TOPIC_DEPLOYMENT, "DeploymentPromotion",
            payload["deployment_id"])
    elif msg_type == "service_registration_upsert":
        for s in payload.get("services", []):
            add(TOPIC_SERVICE, "ServiceRegistration", s.service_name,
                s.namespace, s)
    elif msg_type == "service_registration_delete":
        for rid in payload.get("ids", []):
            add(TOPIC_SERVICE, "ServiceDeregistration", rid)
        for aid in payload.get("alloc_ids", []):
            add(TOPIC_SERVICE, "ServiceDeregistration", aid)
    return out
