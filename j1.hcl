job "gc-job-1" {
  datacenters = ["dc1"]
  type = "batch"
  group "g" { task "t" { driver = "mock_driver" config { run_for = "120s" } } }
}
