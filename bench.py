"""Benchmark: batched placement throughput on the device kernel.

Scenario = BASELINE.json config #2: a batch job with count=10k placed
over 1k in-memory nodes — the pure BinPackIterator path. The reference's
headline number for this shape is the C1M claim of "thousands of
container deployments per second" (~5k/s cluster-wide on 5k nodes,
/root/reference/website/pages/intro/use-cases.mdx:56-58); vs_baseline is
measured placements/sec over that 5000/s reference rate.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np


def main() -> None:
    from nomad_tpu.ops.select import SelectKernel, SelectRequest

    n_nodes = 1000
    total_placements = 10240
    batch = 10240  # whole job in ONE device dispatch (scan carries state)

    rng = np.random.RandomState(42)
    capacity = np.tile(np.array([[4000.0, 8192.0, 102400.0]], np.float32),
                       (n_nodes, 1))
    used = (capacity * rng.uniform(0.0, 0.2, size=(n_nodes, 3))).astype(np.float32)
    ask = np.array([100.0, 100.0, 10.0], np.float32)  # mock batch job task

    kernel = SelectKernel()

    def make_req(count):
        return SelectRequest(
            ask=ask, count=count,
            feasible=np.ones(n_nodes, dtype=bool),
            capacity=capacity, used=used.copy(),
            desired_count=float(count),
            tg_collisions=np.zeros(n_nodes, np.int32),
            job_count=np.zeros(n_nodes, np.int32),
        )

    # warm-up / compile
    kernel.select(make_req(batch))

    placed = 0
    t0 = time.perf_counter()
    remaining = total_placements
    dispatch_times = []
    while remaining > 0:
        count = min(batch, remaining)
        t_d = time.perf_counter()
        res = kernel.select(make_req(count))
        dispatch_times.append(time.perf_counter() - t_d)
        placed += res.placed
        remaining -= count
    elapsed = time.perf_counter() - t0

    per_sec = placed / elapsed
    baseline_rate = 5000.0  # C1M: "thousands of deployments per second"
    print(json.dumps({
        "metric": "placements_per_sec_batch10k_1k_nodes",
        "value": round(per_sec, 1),
        "unit": "placements/s",
        "vs_baseline": round(per_sec / baseline_rate, 2),
    }))


if __name__ == "__main__":
    main()
