"""Benchmark: batched placement throughput, kernel-level and end-to-end.

Headline metric = BASELINE.json config #2 on the raw device kernel: a
batch job with count=10k placed over 1k in-memory nodes — the pure
BinPackIterator path. The reference's headline number for this shape is
the C1M claim of "thousands of container deployments per second" (~5k/s
cluster-wide on 5k nodes,
/root/reference/website/pages/intro/use-cases.mdx:56-58); vs_baseline is
measured placements/sec over that 5000/s reference rate.

Extra keys on the same line (nomad_tpu/bench/ladder.py): the SAME
scenario driven end-to-end through the full control plane
(e2e_placements_per_sec, e2e_vs_baseline), ladder #3 service-job p99
Process() latency over 10k nodes (service_p99_ms; BASELINE target
<= 100 ms), and ladder #4 preemption throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Robustness: the ambient accelerator is probed in a subprocess with a
timeout before this process touches JAX; if the probe fails or hangs the
run falls back to the host CPU platform, and a hard failure still emits
the JSON line with an "error" field instead of a traceback (VERDICT
round 1, item 1b).
"""

import json
import sys
import time
import traceback

import numpy as np

BASELINE_RATE = 5000.0  # C1M: "thousands of deployments per second"


def _init_backend() -> str:
    """Pick a usable JAX backend BEFORE this process initializes one.
    The ambient platform (the axon TPU under the driver) is probed in a
    subprocess with a timeout, because a dead tunnel hangs jax.devices()
    rather than raising; post-init platform switches are silently ignored
    by jax, so the decision must be made up front. Returns the platform
    name in use."""
    from nomad_tpu.utils.platform import force_cpu_platform, probe_accelerator

    platform = probe_accelerator(timeout_s=120.0)
    if platform is None or platform == "cpu":
        force_cpu_platform(1)
        platform = "cpu"
    return platform


def run_kernel_bench():
    """Sustained kernel placement throughput: FOUR 10k-instance batch
    jobs over a 1k-node table placed in ONE device dispatch
    (select_many — multi-eval batching, SURVEY §2.6 row 1: the broker
    queues evals and the device should be fed whole batches of them).
    Over a tunneled TPU a sequential per-eval measurement is bounded by
    2 round trips per eval regardless of kernel speed; sustained
    placements/sec is the metric the C1M baseline states."""
    from nomad_tpu.ops.select import SelectKernel, SelectRequest

    n_nodes = 1000
    batch = 10240  # whole job in ONE device dispatch (kernel carries state)
    pipeline = 8   # batches in flight, like queued evals on the broker

    rng = np.random.RandomState(42)
    capacity = np.tile(
        np.array([[4000.0, 8192.0, 102400.0, 1000.0]], np.float32),
        (n_nodes, 1))
    used = (capacity * rng.uniform(0.0, 0.2, size=(n_nodes, 4))).astype(np.float32)
    ask = np.array([100.0, 100.0, 10.0, 0.0], np.float32)  # mock batch task

    kernel = SelectKernel()

    def make_req(count):
        return SelectRequest(
            ask=ask, count=count,
            feasible=np.ones(n_nodes, dtype=bool),
            capacity=capacity, used=used.copy(),
            desired_count=float(count),
            tg_collisions=np.zeros(n_nodes, np.int32),
            job_count=np.zeros(n_nodes, np.int32),
        )

    # warm-up / compile
    kernel.select_many([make_req(batch) for _ in range(pipeline)])

    # median of 5 timed rounds: a tunneled device has high dispatch
    # variance and a single sample misstates steady-state throughput
    rates = []
    for _ in range(5):
        t0 = time.perf_counter()
        results = kernel.select_many([make_req(batch)
                                      for _ in range(pipeline)])
        placed = sum(r.placed for r in results)
        elapsed = time.perf_counter() - t0
        rates.append(placed / elapsed)
    rates.sort()
    return rates[2]


def main() -> None:
    out = {
        "metric": "placements_per_sec_batch10k_1k_nodes",
        "value": 0.0,
        "unit": "placements/s",
        "vs_baseline": 0.0,
    }
    import os
    # governed soak runs must be attributable: record whether the
    # runtime sanitizer's kernel-boundary guards were armed
    from nomad_tpu.analysis.sanitizer import enabled as _sanitize_on
    out["sanitizer"] = "on" if _sanitize_on() else "off"
    # runtime race sanitizer engagement (ISSUE 14): governed runs must
    # record whether the lock shims were instrumenting the process
    from nomad_tpu.analysis.race import enabled as _race_on
    out["race"] = "on" if _race_on() else "off"
    # micro-batch gateway engagement must be attributable per round
    # (ISSUE 7): record whether the env kill switch disabled it
    out["microbatch"] = ("off" if os.environ.get(
        "NOMAD_TPU_MICROBATCH", "1") in ("0", "off") else "on")
    # write-side ingest gateway engagement (ISSUE 19), same discipline
    from nomad_tpu.server.ingest import ingest_batch_enabled
    out["ingest"] = "on" if ingest_batch_enabled() else "off"
    # retained telemetry collector engagement (ISSUE 11)
    from nomad_tpu.telemetry import enabled as _telemetry_on
    out["telemetry"] = "on" if _telemetry_on() else "off"
    quick = os.environ.get("NOMAD_TPU_BENCH_QUICK", "") not in ("", "0")
    try:
        platform = _init_backend()
        # per-stage breakdown (ISSUE 2 satellite): every pipeline stage
        # (host table build / H2D / kernel / D2H / plan apply / broker
        # ack) accumulates wall clock for the whole run and the shares
        # land in the artifact — the kernel-vs-e2e gap is attributable
        # per round instead of inferred
        from nomad_tpu.utils import stages
        stages.enable()
        per_sec = run_kernel_bench()
        out.update({
            "value": round(per_sec, 1),
            "vs_baseline": round(per_sec / BASELINE_RATE, 2),
            "platform": platform,
        })
    except Exception as e:
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(out))
        return

    # the raw-kernel phase is all `kernel` stage by construction;
    # reset so the emitted breakdown attributes the END-TO-END phases
    # (ladder + C2M), where the host-vs-device split is the question
    stages.enable(reset=True)

    # End-to-end ladder (VERDICT r1 item 4): full scheduler path, not
    # just the kernel — BASELINE configs #2/#3/#4. A ladder failure
    # still emits the headline line.
    try:
        from nomad_tpu.bench.ladder import run_ladder
        out.update(run_ladder(quick=quick))
        out["e2e_vs_baseline"] = round(
            out["e2e_placements_per_sec"] / BASELINE_RATE, 2)
    except Exception as e:
        traceback.print_exc(file=sys.stderr)
        out["ladder_error"] = f"{type(e).__name__}: {e}"

    # mesh-residency ladder (ISSUE 12): the same warm eval stream over
    # the forced 8-device CPU mesh vs single-device, with the sharded
    # resident table's H2D economics (zero full re-uploads steady
    # state) recorded. Subprocess: the mesh needs 8 virtual devices
    # configured before jax init, and this process already picked one.
    try:
        from nomad_tpu.bench.multichip import run_multichip_bench
        out.update(run_multichip_bench(quick=quick))
    except Exception as e:
        traceback.print_exc(file=sys.stderr)
        out["multichip_error"] = f"{type(e).__name__}: {e}"

    # ladder #5 — C2M at its real scale (BASELINE config #5): 50k nodes
    # pre-loaded with 2M running allocs (40k through the real scheduler
    # path, the rest via the replay loader), then batch + service evals
    # against the resident table over the full 2M-row alloc table.
    try:
        from nomad_tpu.bench.ladder import bench_c2m_scale
        c2m_allocs = int(os.environ.get("NOMAD_TPU_C2M_ALLOCS", 2_000_000))
        if c2m_allocs > 0:
            out.update(bench_c2m_scale(n_nodes=50000,
                                       seed_allocs=c2m_allocs,
                                       n_service=20))
    except Exception as e:
        traceback.print_exc(file=sys.stderr)
        out["c2m_error"] = f"{type(e).__name__}: {e}"

    # per-stage attribution over the e2e phases, plus the resident-
    # table maintenance counters (full builds vs delta refreshes vs
    # device scatters) — the steady-state story in one place
    try:
        out["stage_breakdown"] = stages.snapshot()
        # eval flight recorder (ISSUE 9): the per-stage PERCENTILE
        # breakdown (sums can't show bimodality), whether tracing was
        # armed for this round, and the tail-exemplar evidence — a TPU
        # run comes back with the anatomy of its worst evals, and the
        # completeness bit proves the span tree covered enqueue->ack
        # with gateway + commit attrs populated
        from nomad_tpu.trace import tracer as flight
        out["trace"] = "on" if flight.enabled() else "off"
        out["stage_percentiles"] = flight.stage_percentiles()
        exemplars = flight.exemplars()
        out["trace_exemplars"] = len(exemplars)
        need = {"queue_wait", "sched_host", "plan_verify",
                "plan_commit", "broker_ack"}

        def _complete(t):
            names = {sp["name"] for sp in t["spans"]}
            gw = any(sp["name"] == "gateway_wait"
                     and "batch" in sp.get("attrs", {})
                     for sp in t["spans"])
            cm = any(sp["name"] == "plan_commit"
                     and "group" in sp.get("attrs", {})
                     for sp in t["spans"])
            return need <= names and gw and cm

        out["trace_exemplar_complete"] = any(
            _complete(t) for t in exemplars)
        # which exemplars survive worst-K retention is load-dependent
        # (a drift auto-pin mid-bench can park early traces), so the
        # CI-stable completeness claim scans the whole recorder: a
        # complete capture exists SOMEWHERE in exemplars ∪ ring
        out["trace_capture_complete"] = (
            out["trace_exemplar_complete"]
            or any(_complete(t) for t in flight.recent(512)))
        if exemplars:
            out["trace_exemplar_max_ms"] = round(
                max(t["total_ms"] for t in exemplars), 1)
        from nomad_tpu.ops.select import cost_model
        from nomad_tpu.ops.tables import BUILD_STATS
        out["table_build_stats"] = dict(BUILD_STATS)
        out["dispatch_cost_model"] = cost_model.snapshot()
        # device economics (ISSUE 11): pad waste and per-arm dispatch
        # seconds / fresh-compile counts over the whole run — the
        # first-class instruments the real-TPU validation campaign
        # reads (a pad_waste_ratio near 1.0 at small scale is the
        # power-of-two bucketing's floor cost; the number that matters
        # is the C2M-scale one)
        from nomad_tpu.ops.select import device_stats_snapshot
        dev = device_stats_snapshot()
        out["pad_waste_ratio"] = dev["pad_waste_ratio"]
        out["device_dispatch_s"] = dev["dispatch_s"]
        out["device_dispatches"] = dev["dispatches"]
        out["device_compiles"] = dev["compiles"]
        from nomad_tpu.analysis.sanitizer import traces
        out["lint_recompiles"] = traces.per_kernel()
        # group-commit applier + cross-eval engine reuse (ISSUE 4):
        # group sizing and the host-phase reuse hit rate, so the next
        # TPU run can confirm the commit half of the e2e gap closed
        from nomad_tpu.server.plan_applier import GROUP_STATS
        out["plan_group_stats"] = dict(GROUP_STATS)
        out["plan_group_mean_size"] = round(
            GROUP_STATS["plans"] / max(GROUP_STATS["groups"], 1), 2)
        out["plan_group_conflict_retries"] = \
            GROUP_STATS["conflict_retries"]
        # write-side ingest coalescing over the whole run (ISSUE 19):
        # the cross-server aggregate behind the bench_ingest cell
        from nomad_tpu.server.ingest import INGEST_STATS
        out["ingest_stats"] = dict(INGEST_STATS)
        out["ingest_mean_batch"] = round(
            INGEST_STATS["writes"] / max(INGEST_STATS["batches"], 1), 2)
        from nomad_tpu.scheduler.stack import engine_cache_stats
        ec = engine_cache_stats()
        out["engine_reuse"] = ec
        out["engine_reuse_hit_rate"] = round(
            ec["mask_hits"] / max(ec["mask_hits"] + ec["mask_misses"],
                                  1), 4)
        # columnar reconcile engine (ISSUE 6): the tasks_updated memo
        # over the whole run — the deployment-wave scenario reports its
        # own deploy_wave_* keys for the on-vs-off comparison
        from nomad_tpu.scheduler.stack import (tasks_updated_hit_rate,
                                               tasks_updated_stats)
        out["tasks_updated"] = tasks_updated_stats()
        out["tasks_updated_hit_rate"] = round(tasks_updated_hit_rate(),
                                              4)
    except Exception as e:   # pragma: no cover — defensive
        out["stage_error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(out))


if __name__ == "__main__":
    main()
