job "gc-job-3" {
  datacenters = ["dc1"]
  type = "batch"
  group "g" { task "t" { driver = "mock_driver" config { run_for = "120s" } } }
}
